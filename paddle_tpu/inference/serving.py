"""Continuous-batching serving engine over the paged KV cache — the
TPU-native equivalent of the reference's serving decode stack
(block_multihead_attention + FusedMultiTransformer cache decode +
fused_get_padding_offset plumbing; reference:
/root/reference/python/paddle/incubate/nn/functional/block_multihead_attention.py:19,
/root/reference/python/paddle/incubate/nn/layer/fused_transformer.py:994).

Design:
- ONE compiled step program with fixed shapes: a packed token buffer
  [token_budget] carries a mix of decode tokens (1 per running sequence) and
  prefill chunks (admitted prompts are fed chunk-by-chunk). Sequences of any
  length enter and retire without recompilation — admission/eviction is pure
  host bookkeeping over the block free-list.
- KV lives in per-layer block pools [num_blocks, KV, bs, D] indexed through
  per-sequence block tables (ops/paged_attention.py). Sampling runs
  in-graph — temperature / top-k / top-p with per-request PRNG keys and
  optional logprobs; ``temperature=0`` (the default) takes the exact
  argmax path, so greedy serving is bit-identical to the pre-sampling
  engine.  The host reads back [B] next-token ids per step (one small
  transfer, the same shape every step).
- MEGASTEP decode (ISSUE 9, mixed-phase since ISSUE 16): ``step()`` runs
  K iterations inside ONE compiled ``lax.scan`` instead of K host round
  trips — the host syncs only at megastep boundaries (finish / admission).
  ARMING RULE: the scan arms whenever any scheduled row is DECODING
  (``megastep_k > 1``).  A pure-decode batch runs the tight [B]-token
  scan (mq=1); a batch mixing decode rows with prefilling rows runs the
  MIXED scan (mq=block_size): each iteration processes, per row, either
  one decode token or one block-size prompt chunk — prompt chunks are fed
  as data through a ``prefill_pos`` carry against a host-staged prompt
  window, so chunked prefill adds no shape axis and no recompile.  Under
  open-loop admission the megastep therefore never disarms just because
  some row is still prefilling (Sarathi/vLLM-style stall-free chunked
  prefill).  Rows that finish mid-scan (EOS or token budget) are masked:
  their carry freezes and their sampled tokens are dropped on the host.
  K rounds up to a power of two (bounded compile count) capped at
  ``megastep_k``; ``megastep_k=1`` restores per-token stepping.  The
  int8 KV cache rides the pure-decode scan too (its per-(slot, kv-head)
  scales travel in the scan carry; enc=0 rows pass them through
  untouched) — only its one-shot PREFILL keeps the single-step path,
  because dynamic scales freeze at prefill.  Per-row DEADLINE budgets
  ride the carry as data (iterations, not wall clock — compiled bodies
  never read a clock): a row whose budget hits zero freezes in-graph,
  so deadline overshoot inside a megastep is ZERO tokens once a
  per-iteration time estimate exists (``deadline_token_seconds`` or the
  engine's measured EWMA); the host-side typed shed stays the
  control plane's job at harvest (control_plane.py).
- This is the vLLM-style schedule expressed the XLA way: static shapes +
  dynamic lengths as data, not as shapes.
- Automatic prefix caching (on by default, ``prefix_cache="auto"``):
  ``BlockManager`` refcounts blocks and keeps a content-hash index chained
  over ``(parent_hash, block_size token ids)`` — a retiring or evicted
  request publishes its FULL blocks, and admission maps the longest cached
  full-block prefix of a new prompt straight into its block table with
  ``prefill_pos`` advanced past it, so the compiled step only ever feeds
  the uncached tail (``prefill_pos`` is data, not shape: no recompile, no
  in-graph change).  Granularity is whole blocks: a partial tail block is
  never shared, and a fully-cached block-aligned prompt re-feeds exactly
  one token into a copy-on-write fork of its last block (compute must see
  ≥ 1 token to produce logits; the shared original stays read-only).
  Refcount-0 published blocks park in an LRU that ``allocate`` evicts
  only when the true free list is empty.  ``cache_quant='int8'`` is
  excluded by a hard error: its per-(slot, kv-head) dynamic scales make
  block payloads writer-specific, so shared blocks would dequantize
  garbage.

Frontend → fleet → engine split: the engine is a pure execution loop —
it admits whatever is in its queue, steps, and retires.  Policy
(priority classes, deadlines, admission control, routing across replicas,
failover) lives in ``ServingFrontend`` (control_plane.py), which drives
``step()`` and harvests via ``pop_finished()``.  The frontend does not
care where an engine runs: in-process ``ServingEngine`` objects and
``fleet.RemoteReplica`` adapters (the same surface proxied over RPC to a
``tools/serving_worker.py`` process on this or another host) are
interchangeable replicas; ``fleet.ServingFleet`` spawns/drains those
workers and layers heartbeats + autoscaling on top.  The preemption contract: ``evict(rid)``
removes a queued or running request mid-flight, frees its blocks and slot
immediately (BlockManager tolerates this and guards double-frees), and
returns the request object; the caller re-queues it with ``prompt +
generated`` as the new prefill.  Greedy decode is deterministic, so a
preempted-then-resumed request reproduces the unpreempted token stream
exactly.
"""
from __future__ import annotations

import hashlib
import time
from collections import Counter, OrderedDict
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.paged_attention import blha_attention
from .faults import register_failpoint

__all__ = ["BlockManager", "ServingRequest", "ServingEngine",
           "SamplingParams", "prefix_block_hash", "prompt_block_hashes",
           "ngram_draft"]
# the policy layer above this engine lives in control_plane.py
# (ServingFrontend) and metrics.py (ServingMetrics)

# rolling weight swaps (ISSUE 18): fired at the top of load_weights,
# BEFORE any state is touched, so an injected swap fault leaves the old
# weights fully serving — the rolling_swap driver keeps the replica on
# its previous version and counts weight_swap_failures_total
WEIGHTS_SWAP = register_failpoint("weights.swap")

# speculative decoding (ISSUE 19): both sites DEGRADE, never corrupt —
# a drafting fault empties that row's draft (the verify still commits
# its one non-spec token), a verify fault falls the whole step back to
# the megastep/single-step path.  Either way the emitted token stream
# is bit-identical to spec-off; chaos asserts exactly that.
SPEC_DRAFT = register_failpoint("engine.spec_draft")
SPEC_VERIFY = register_failpoint("engine.spec_verify")


@dataclass
class SamplingParams:
    """Per-request decode sampling knobs, applied IN-GRAPH.

    ``temperature=0`` (default) is exact greedy argmax — bit-identical to
    the engine's historical path, which is what the preempt/resume,
    prefix-cache-parity, and chaos token-identity contracts are stated
    over.  With ``temperature > 0``: logits are scaled, the top-k then
    top-p (nucleus) filters apply, and the token is drawn with a
    per-request PRNG key derived ONLY from ``(seed, sample index)`` —
    never from batch slot, megastep size, or replica — so the same seed
    replays the same token stream across preemption, failover resume,
    and worker restarts.  ``logprobs=True`` additionally returns the
    log-softmax of the RAW logits at each sampled token (temperature- and
    filter-independent, so greedy and sampled runs report comparable
    values)."""

    temperature: float = 0.0
    top_k: int = 0          # 0 = no top-k filter
    top_p: float = 1.0      # 1.0 = no nucleus filter
    seed: int = 0
    logprobs: bool = False
    # opt OUT of speculative decoding for this request (ISSUE 19).  Only
    # effective on engines built with spec_k > 0; spec-on is token-
    # identical to spec-off by contract, so the toggle exists for
    # latency-shape control (verify batches commit tokens in bursts),
    # not correctness.
    spec: bool = True

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        # the seed feeds an int32 PRNG-key array inside the step program:
        # reject out-of-range here (submit time) — otherwise numpy raises
        # mid-step and the control plane reads that as a replica DEATH,
        # burning the whole retry budget on one bad user parameter
        if not 0 <= self.seed < 2 ** 31:
            raise ValueError("seed must be in [0, 2**31)")

    @classmethod
    def coerce(cls, v) -> "SamplingParams":
        if v is None:
            return cls()
        if isinstance(v, cls):
            return v
        return cls(**dict(v))   # plain dict: the RPC wire format

    def to_wire(self) -> Dict:
        """The dict form shipped over RPC (and back through ``coerce``) —
        the ONE place the field list is enumerated, so a new sampling
        knob cannot be silently dropped at a transport boundary."""
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed,
                "logprobs": self.logprobs, "spec": self.spec}


def _sample_tokens(logits, temps, top_ks, top_ps, seeds, sample_pos,
                   return_probs: bool = False):
    """In-graph next-token selection for one batch of logits rows [B, V].

    Greedy rows (``temps <= 0``) take the exact float32 argmax the engine
    always used.  Sampled rows divide by temperature, apply top-k and
    top-p in sorted space (ties at the threshold are kept), and draw via
    ``jax.random.categorical`` under a key folded from ``(seed,
    sample_pos)``.  A ``lax.cond`` skips the two [B, V] sorts entirely
    when the whole batch is greedy, so the default serving path pays
    nothing for the sampling machinery.  Returns (next_token [B] int32,
    raw-logit logprob of that token [B] float32).

    ``return_probs=True`` (ISSUE 11 satellite; trace-time constant)
    additionally returns the renormalized POST-top-k/top-p distribution
    the token was actually drawn from, [B, V] float32 — a one-hot at the
    argmax for greedy rows — which is exactly the q(x) a speculative-
    decode verifier needs.  The drawn token is bit-identical either way
    (same filtered logits, same key; categorical is shift-invariant),
    but the probs path always computes the filter, so the all-greedy
    sort skip is forfeited — keep it off for plain serving."""
    lg = logits.astype(jnp.float32)
    B, V = lg.shape
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)

    def _filtered(scaled):
        srt = jnp.sort(scaled, axis=-1)[:, ::-1]            # descending
        kth = jnp.take_along_axis(
            srt, jnp.clip(top_ks - 1, 0, V - 1)[:, None], axis=-1)
        keep_k = (top_ks[:, None] <= 0) | (scaled >= kth)
        probs_srt = jax.nn.softmax(srt, axis=-1)            # sorted probs
        csum = jnp.cumsum(probs_srt, axis=-1)
        # nucleus cutoff: the prob of the first sorted token at which the
        # cumulative mass reaches p (so at least one token always stays)
        first = jnp.argmax(csum >= top_ps[:, None], axis=-1)
        cutoff = jnp.take_along_axis(probs_srt, first[:, None], axis=-1)
        probs = jax.nn.softmax(scaled, axis=-1)
        keep_p = (top_ps[:, None] >= 1.0) | (probs >= cutoff)
        return jnp.where(keep_k & keep_p, scaled, -jnp.inf)

    def _draw(filt):
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p)
        )(seeds, sample_pos)
        return jax.vmap(jax.random.categorical)(keys, filt).astype(jnp.int32)

    if return_probs:
        filt = _filtered(lg / jnp.maximum(temps, 1e-6)[:, None])
        nxt = jnp.where(temps <= 0.0, greedy, _draw(filt)).astype(jnp.int32)
        sample_probs = jnp.where(
            (temps <= 0.0)[:, None],
            jax.nn.one_hot(greedy, V, dtype=jnp.float32),
            jax.nn.softmax(filt, axis=-1))
        logprob = jnp.take_along_axis(jax.nn.log_softmax(lg, axis=-1),
                                      nxt[:, None], axis=-1)[:, 0]
        return nxt, logprob, sample_probs

    drawn = jax.lax.cond(jnp.all(temps <= 0.0), lambda _: greedy,
                         lambda _: _draw(
                             _filtered(lg / jnp.maximum(temps, 1e-6)[:, None])),
                         None)
    nxt = jnp.where(temps <= 0.0, greedy, drawn).astype(jnp.int32)
    logprob = jnp.take_along_axis(jax.nn.log_softmax(lg, axis=-1),
                                  nxt[:, None], axis=-1)[:, 0]
    return nxt, logprob, None


def ngram_draft(history: Sequence[int], k: int,
                max_ngram: int = 3) -> List[int]:
    """Model-free n-gram / prompt-lookup drafting (Saxena 2023): find
    the most recent EARLIER occurrence of the history's longest matching
    tail n-gram (n = ``max_ngram`` down to 1) and propose up to ``k``
    tokens of its continuation.  Pure Python over ints — deterministic,
    seed-free, and identical across processes, so replica failover and
    journal replay re-draft (and hence re-verify) the exact same
    proposals.  Operates on ONE request's ``prompt + generated`` history
    only; no cross-request state exists to contaminate.  Returns ``[]``
    when the history is empty/too short or no tail n-gram recurs —
    drafting is best-effort, the verify commits >= 1 token either way."""
    h = [int(t) for t in history]
    n_hist = len(h)
    if k <= 0 or n_hist < 2:
        return []
    for n in range(min(int(max_ngram), n_hist - 1), 0, -1):
        pat = h[-n:]
        for i in range(n_hist - n - 1, -1, -1):
            if h[i:i + n] == pat:
                return h[i + n:i + n + k]
    return []


def prefix_block_hash(parent: Optional[str], tokens: Sequence[int]) -> str:
    """Chain hash of ONE full block of token ids:
    ``blake2b(parent_hash, token bytes)``.  The chaining means a block's
    hash commits to the entire token prefix before it, so equal hashes ⇒
    equal KV content.  blake2b (not builtin ``hash``, which is randomized
    per process) keeps hashes comparable across worker processes — the
    frontend's prefix-affinity routing matches its own prompt hashes
    against hash sets shipped from remote replicas."""
    h = hashlib.blake2b(digest_size=16)
    h.update(parent.encode() if parent else b"\x00root")
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.hexdigest()


def prompt_block_hashes(tokens: Sequence[int], block_size: int) -> List[str]:
    """Chain hashes for every FULL block of ``tokens`` (a partial tail
    block is never cached or matched — it would alias every continuation
    sharing its first few tokens)."""
    out: List[str] = []
    parent = None
    for i in range(len(tokens) // block_size):
        parent = prefix_block_hash(
            parent, tokens[i * block_size:(i + 1) * block_size])
        out.append(parent)
    return out


class BlockManager:
    """Host-side refcounted allocator over the global block pool, with a
    content-hash index for automatic prefix caching.

    A block is in exactly one of three states:

    * **free**   — on the free list; the next ``allocate`` may return it.
    * **live**   — refcount ≥ 1: owned by one or more sequences.  ``fork``
      shares a live (or cached) block with another sequence read-only;
      ``free`` decrements and only releases at refcount 0.
    * **cached** — refcount 0 but content-addressable: ``publish`` gave it
      a chain hash, so when its last owner freed it, it was parked in an
      LRU instead of hard-freed.  ``lookup`` + ``fork`` revive it for a
      new sequence; ``allocate`` evicts from the LRU (oldest first,
      dropping the hash mapping) only when the true free list is empty.

    ``free`` rejects double-frees loudly: releasing a block more times
    than it has owners would hand the same block to two sequences on the
    next ``allocate`` and silently corrupt both KV streams (the failure
    mode is token garbage long after the actual bug).  Mid-flight release
    of a live request's blocks (eviction/preemption) is fine — that is
    the normal path for ``ServingEngine.evict``."""

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, -1, -1))
        self._ref: Dict[int, int] = {}          # live blocks only
        self._hash_of: Dict[int, str] = {}      # published block -> hash
        self._block_of: Dict[str, int] = {}     # hash -> published block
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # cached, ref 0
        self.evictions = 0   # cached blocks dropped to satisfy allocate

    def can_allocate(self, n: int) -> bool:
        return len(self._free) + len(self._lru) >= n

    def allocate(self, n: int) -> List[int]:
        if not self.can_allocate(n):
            raise RuntimeError(f"block pool exhausted (need {n}, "
                               f"free {self.num_free})")
        out: List[int] = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                # true free list empty: evict the least-recently-cached
                # block (its KV becomes unreachable — drop the hash)
                b, _ = self._lru.popitem(last=False)
                h = self._hash_of.pop(b)
                del self._block_of[h]
                self.evictions += 1
            self._ref[b] = 1
            out.append(b)
        assert len(set(out)) == len(out), \
            f"free-list corruption: allocate returned duplicate ids {out}"
        return out

    def free(self, blocks: List[int]):
        counts = Counter(blocks)
        internal = sorted(b for b, c in counts.items() if c > 1)
        bad = sorted(b for b in counts if not 0 <= b < self.num_blocks)
        dup = sorted(b for b in counts
                     if 0 <= b < self.num_blocks and b not in internal
                     and self._ref.get(b, 0) < counts[b])
        if dup or internal or bad:
            raise RuntimeError(
                "BlockManager.free: "
                + "; ".join(filter(None, [
                    f"double-free of block ids {dup}" if dup else "",
                    f"ids repeated in the freed list {internal}"
                    if internal else "",
                    f"ids outside the pool {bad}" if bad else ""])))
        for b in blocks:
            self._ref[b] -= 1
            if self._ref[b] > 0:
                continue          # still shared with another sequence
            del self._ref[b]
            if b in self._hash_of:
                self._lru[b] = None   # published: park evictable, reusable
            else:
                self._free.append(b)

    def fork(self, block: int):
        """Hand ``block`` to one more sequence read-only (refcount++).  A
        cached (refcount-0, LRU-parked) block is revived: pulled out of
        the LRU with refcount 1.  Forking a free block is a bug."""
        if not 0 <= block < self.num_blocks:
            raise RuntimeError(f"BlockManager.fork: id {block} outside the "
                               f"pool of {self.num_blocks}")
        if block in self._lru:
            del self._lru[block]
            self._ref[block] = 1
        elif self._ref.get(block, 0) > 0:
            self._ref[block] += 1
        else:
            raise RuntimeError(
                f"BlockManager.fork: block {block} is on the free list — "
                "only live or cached blocks can be shared")

    def lookup(self, h: str) -> Optional[int]:
        """Block currently holding the content with chain hash ``h``
        (live or cached), or None."""
        return self._block_of.get(h)

    def publish(self, block: int, h: str) -> bool:
        """Register ``block``'s content under chain hash ``h`` so a later
        ``free`` parks it in the LRU (reusable) instead of hard-freeing.
        No-op (False) when the hash is already mapped — first publisher
        wins; chained hashing guarantees the content is identical — or
        when the block already carries a hash."""
        if h in self._block_of or block in self._hash_of:
            return False
        if self._ref.get(block, 0) <= 0:
            raise RuntimeError(
                f"BlockManager.publish: block {block} is not live — publish "
                "before freeing (free() is what parks published blocks)")
        self._block_of[h] = block
        self._hash_of[block] = h
        return True

    def ref_count(self, block: int) -> int:
        return self._ref.get(block, 0)

    def cached_hashes(self) -> Set[str]:
        """Chain hashes currently content-addressable (live or cached) —
        the engine's prefix-affinity summary shipped to the frontend."""
        return set(self._block_of)

    def drop_cached(self) -> int:
        """Invalidate the content-addressed cache: evictable (refcount-0)
        published blocks return to the free list and EVERY hash mapping
        is dropped (a live publisher keeps its block but loses the hash,
        so a later ``free`` hard-frees instead of parking).  The weight-
        swap path calls this — KV computed under the old weights must
        never be matched by a new-version prompt.  Returns the number of
        hashes invalidated."""
        n = len(self._block_of)
        for b in self._lru:
            self._free.append(b)
        self._lru.clear()
        self._block_of.clear()
        self._hash_of.clear()
        return n

    @property
    def num_free(self) -> int:
        """Blocks allocatable right now: truly free plus cached-evictable.
        (Admission headroom math must see cached blocks as capacity, or a
        warm cache would look like an exhausted pool.)"""
        return len(self._free) + len(self._lru)

    @property
    def num_cached(self) -> int:
        return len(self._block_of)

    @property
    def num_evictable(self) -> int:
        return len(self._lru)


@dataclass
class ServingRequest:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    sampling: SamplingParams = field(default_factory=SamplingParams)
    # sample index of this request's FIRST new token: a preempted request
    # resumed with prompt+generated as its new prefill passes the number
    # of tokens already sampled here, so the seeded key stream continues
    # exactly where the evicted run stopped
    sample_offset: int = 0
    # tracing wire context (ISSUE 15): {"trace", "span", "parent", "rid"}
    # stamped by the frontend (rid = the FRONTEND rid); engine lifecycle
    # events (prefill done, megastep boundaries) are recorded under it
    trace: Optional[Dict] = None
    # absolute engine-clock deadline (None = no deadline): set from the
    # ``deadline_s`` admission kwarg; megastep launches convert it into
    # an in-graph iteration budget (see _deadline_budgets)
    deadline_t: Optional[float] = None
    # runtime state
    generated: List[int] = field(default_factory=list)
    logprob_values: List[float] = field(default_factory=list)
    blocks: List[int] = field(default_factory=list)
    prefill_pos: int = 0          # prompt tokens already cached
    cached_prefix_tokens: int = 0  # of those, tokens REUSED from the cache
    chunks_fed: int = 0           # prompt chunks fed so far (trace index)
    slot: int = -1                # batch row while active
    done: bool = False

    @property
    def in_prefill(self) -> bool:
        return self.prefill_pos < len(self.prompt)

    @property
    def context_len(self) -> int:
        return self.prefill_pos + len(self.generated)


# Process-wide cache of compiled serving programs, keyed by the static
# configuration the _build_* closures bake into the trace (model dims +
# engine geometry + quant/capture flags).  Weights, caches and rope are
# call ARGUMENTS — the trace never bakes their values, and jax.jit
# already re-specializes on argument shapes/dtypes/pytree structure —
# so every engine built with the same geometry shares one jitted
# program AND its XLA compile cache.  N engines over one model costs
# one set of multi-second compiles instead of N.
_PROGRAM_CACHE: Dict[tuple, dict] = {}


def _np_dtype(name: str) -> np.dtype:
    """Numpy dtype for a cache dtype's string form.  ``bfloat16`` (and
    friends) only resolve once ml_dtypes' registrations are imported —
    jax depends on it, so the lazy import never fails in practice."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class ServingEngine:
    """Continuous batching for a LlamaForCausalLM (single process).

    >>> eng = ServingEngine(model, max_batch_size=4, max_seq_len=256)
    >>> rid = eng.add_request([1, 5, 7], max_new_tokens=16)
    >>> outputs = eng.run()   # {rid: [token, ...]}
    """

    # data-plane listener endpoint ("host:port"), stamped by
    # blockwire.BlockWireServer when this engine serves direct
    # worker-to-worker block pulls; None = relay-only (KVFabric.pull's
    # degrade ladder skips the wire rung)
    wire_endpoint: Optional[str] = None

    def __init__(self, model, max_batch_size: int = 4, max_seq_len: int = 256,
                 block_size: int = 16, token_budget: int = 32,
                 num_blocks: Optional[int] = None, cache_dtype=None,
                 cache_quant: str = "none", prefix_cache="auto",
                 megastep_k: int = 8, fault_injector=None,
                 capture_sample_probs: bool = False,
                 trace_recorder=None,
                 deadline_token_seconds: Optional[float] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 spec_k: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        from .faults import FaultInjector

        # seeded failpoint registry (faults.py): the 'engine.step' site
        # lets a chaos run crash this engine deterministically — incl.
        # poison requests via a match on the active prompts' signatures.
        # None (the default, unless PADDLE_TPU_FAULTS is set) keeps the
        # production step loop at a single attribute test of cost.
        self._faults = (fault_injector if fault_injector is not None
                        else FaultInjector.from_env())
        cfg = model.config
        self.cfg = cfg
        self.B = int(max_batch_size)
        self.T = int(token_budget)
        self.bs = int(block_size)
        self.P = (int(max_seq_len) + self.bs - 1) // self.bs  # blocks/seq
        self.max_seq_len = self.P * self.bs
        nb = num_blocks if num_blocks is not None else self.B * self.P
        self.blocks = BlockManager(int(nb))
        self.H = cfg.num_attention_heads
        self.KV = cfg.num_key_value_heads
        self.D = cfg.head_dim
        self.E = cfg.hidden_size
        self.L = cfg.num_hidden_layers
        if cache_quant not in ("none", "int8"):
            raise ValueError("cache_quant must be 'none' or 'int8'")
        self.cache_quant = cache_quant
        if prefix_cache not in ("auto", True, False):
            raise ValueError("prefix_cache must be 'auto', True, or False")
        if cache_quant == "int8" and prefix_cache is True:
            raise ValueError(
                "prefix_cache cannot be combined with cache_quant='int8': "
                "the int8 cache dequantizes through per-(slot, kv-head) "
                "DYNAMIC scales frozen at each sequence's own prefill, so a "
                "block's uint8 payload is only meaningful under its writer's "
                "scales — a second sequence sharing the block would "
                "dequantize garbage. Use the unquantized cache with the "
                "prefix cache, or pass prefix_cache=False")
        # 'auto' = on wherever it is sound (everything but int8)
        self.prefix_cache_enabled = (cache_quant != "int8"
                                     and prefix_cache in ("auto", True))
        self.prefix_hit_blocks = 0      # full blocks reused from the cache
        self.prefix_miss_blocks = 0     # full prompt blocks that missed
        self.prefill_tokens_computed = 0  # prompt tokens actually fed
        if cache_quant == "int8" and cache_dtype is not None:
            raise ValueError(
                "cache_quant='int8' fixes the cache dtype to uint8 — don't "
                "pass cache_dtype with it")
        if cache_quant == "int8":
            # paged int8 KV (the reference's cache_int8 serving mode):
            # uint8 blocks + per-(slot, kv-head) dynamic scales refreshed by
            # the prefill rows (ops/paged_attention.py quant contract)
            cache_dtype = jnp.uint8
        elif cache_dtype is None:
            cache_dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self._compute_dtype = (jnp.bfloat16 if cfg.dtype == "bfloat16"
                               else jnp.float32)

        self._weights = self._extract_weights(model)
        # rolling weight swaps / tenancy (ISSUE 18): a version label that
        # rides metric + trace attribution, and the model id tenant
        # routing keys on.  Both are plain host state — load_weights
        # replaces the weight pytree without touching the compiled
        # programs (model identity is NOT in _program_key).
        self.weights_version = "v0"
        self.model_id = "default"
        self._rope = self._build_rope(cfg)
        self.key_caches = [jnp.zeros((nb, self.KV, self.bs, self.D), cache_dtype)
                           for _ in range(self.L)]
        self.value_caches = [jnp.zeros_like(self.key_caches[0])
                             for _ in range(self.L)]
        if cache_quant == "int8":
            self.cache_scales = [
                {k: jnp.zeros((self.B, self.KV), jnp.float32)
                 for k in ("kq", "vq", "kd", "vd")} for _ in range(self.L)]
        else:
            self.cache_scales = None
        self.block_tables = np.full((self.B, self.P), -1, np.int32)

        # capture the renormalized post-top-k/top-p distribution each
        # drawn token was sampled from (ISSUE 11 satellite — speculative-
        # decode verification needs q(x), not just the drawn token);
        # engine-local debug/verification knob: costs the [B,V] filter
        # even for greedy batches and is not mirrored over fleet RPC
        self.capture_sample_probs = bool(capture_sample_probs)
        self._queue: List[ServingRequest] = []
        self._active: Dict[int, ServingRequest] = {}
        self._finished: Dict[int, List[int]] = {}
        self._emitted_logprobs: Dict[int, List[float]] = {}
        self._emitted_sample_probs: Dict[int, List[np.ndarray]] = {}
        self._next_rid = 0
        self._free_slots = list(range(self.B - 1, -1, -1))
        # megastep decode: K compiled iterations per host round trip
        # whenever any scheduled row is decoding (1 = per-token stepping);
        # prefilling rows ride the same scan chunk-by-chunk (mixed phase),
        # and int8 KV-quant rides the pure-decode scan with its scales in
        # the carry
        if int(megastep_k) < 1:
            raise ValueError("megastep_k must be >= 1")
        self.megastep_k = int(megastep_k)
        self.megasteps = 0          # megastep program launches (monotone)
        self.megastep_tokens = 0    # tokens emitted via the megastep path
        self.megasteps_mixed = 0    # of those launches, mixed-phase scans
        self.prefill_chunks = 0     # prompt chunks fed inside mixed scans
        # prefill chunk size (ISSUE 19 satellite, first rung toward
        # Sarathi-style budget-adaptive chunking): tokens per prompt
        # chunk inside the mixed-phase scan.  Default = block_size (the
        # historical behavior); <= block_size keeps one chunk inside one
        # KV block's worth of writes.  Trace-shaping (the scan's packed
        # chunk width), hence part of _program_key.
        pc = self.bs if prefill_chunk_tokens is None else int(prefill_chunk_tokens)
        if not 1 <= pc <= self.bs:
            raise ValueError(
                f"prefill_chunk_tokens={pc} must be in [1, block_size="
                f"{self.bs}]")
        self.pc = pc
        # speculative decoding (ISSUE 19): n-gram drafts of up to spec_k
        # tokens per pure-decode row, verified (and committed) by ONE
        # batched forward.  0 (default) disarms the path entirely.
        if int(spec_k) < 0:
            raise ValueError("spec_k must be >= 0")
        self.spec_k = int(spec_k)
        self.spec_accepted_tokens = 0   # draft tokens committed (monotone)
        self.spec_draft_tokens = 0      # draft tokens proposed (monotone)
        self.spec_verify_forwards = 0   # rows scored by verify launches
        # in-graph deadline budgets: seconds one scan iteration costs.
        # An explicit deadline_token_seconds pins it (tests, or operators
        # who measured their hardware); None lets the engine learn an
        # EWMA from measured megastep execute time.  Until some estimate
        # exists, deadline rows fall back to the K-1 boundary bound.
        if deadline_token_seconds is not None and deadline_token_seconds <= 0:
            raise ValueError("deadline_token_seconds must be > 0")
        self._tau_override = deadline_token_seconds is not None
        self._tau = (float(deadline_token_seconds)
                     if deadline_token_seconds is not None else None)
        # per-request tracing (ISSUE 15): an optional FlightRecorder ring.
        # None (the default) keeps every hook at a single attribute test —
        # same zero-cost pattern as self._faults above.
        self.trace_recorder = trace_recorder
        self._clock = clock
        # cumulative host-side seconds per step phase (schedule = admission
        # + batch marshalling, execute = compiled call + device sync,
        # harvest = token/unblocking bookkeeping); surfaced via
        # state_summary() for megastep cost attribution
        self.phase_seconds = {"schedule": 0.0, "execute": 0.0, "harvest": 0.0}
        # Programs are shared process-wide across engines with identical
        # trace-shaping config (see _PROGRAM_CACHE): a fresh engine over
        # an already-served geometry starts with warm compile caches.
        self._programs = _PROGRAM_CACHE.setdefault(self._program_key(), {})
        if "forward" not in self._programs:
            fwd, trunk = self._build_forward()
            self._programs["forward"] = fwd
            self._programs["trunk"] = trunk
        self._forward = self._programs["forward"]
        self._trunk = self._programs["trunk"]
        if "step" not in self._programs:
            self._programs["step"] = self._build_step()
        self._step_fn = self._programs["step"]
        self._mega_fn = self._programs.get("mega")    # lazy: pure-decode scan
        self._mixed_fn = self._programs.get("mixed")  # lazy: mixed-phase scan
        self._spec_fn = self._programs.get("spec")    # lazy: spec verify
        self._cow_fn = self._programs.get("cow")      # lazy: COW block copy
        self._put_fn = self._programs.get("put")      # lazy: block import write
        self.compile_count = 0

    def _program_key(self) -> tuple:
        """Everything the compiled-program closures capture that shapes
        the trace.  Model identity is deliberately NOT part of the key:
        weights/caches/rope enter as arguments, so jit keys their
        shapes/dtypes (and the layer count, via pytree structure)
        itself — two models with the same architecture share programs."""
        return (self.B, self.T, self.bs, self.H, self.KV, self.D, self.E,
                float(self.cfg.rms_norm_eps), self.cache_quant,
                bool(self.capture_sample_probs), self.pc, self.spec_k)

    # ------------------------------------------------------------ weights
    def _extract_weights(self, model):
        def v(t):
            return t._value.astype(self._compute_dtype)

        lm = model.llama
        w = {
            "embed": v(model.llama.embed_tokens.weight),
            "norm": v(lm.norm.weight),
        }
        if model.lm_head is None:
            w["head"] = w["embed"].T
        else:
            w["head"] = v(model.lm_head.weight)
        w["layers"] = []
        for layer in lm.layers:
            a, m = layer.self_attn, layer.mlp
            w["layers"].append({
                "ln1": v(layer.input_layernorm.weight),
                "ln2": v(layer.post_attention_layernorm.weight),
                "wq": v(a.q_proj.weight), "wk": v(a.k_proj.weight),
                "wv": v(a.v_proj.weight), "wo": v(a.o_proj.weight),
                "wg": v(m.gate_proj.weight), "wu": v(m.up_proj.weight),
                "wd": v(m.down_proj.weight),
            })
        return w

    def load_weights(self, model, version: Optional[str] = None,
                     model_id: Optional[str] = None) -> str:
        """Swap in ``model``'s weights WITHOUT recompiling: weights enter
        the compiled programs as call arguments, so same-architecture
        models reuse every cached program (``_program_key`` excludes
        model identity on purpose).  The caller (``rolling_swap`` or
        tenant swap-on-demand routing) is responsible for draining the
        engine first — active sequences would otherwise continue under
        the new weights mid-stream.

        The prefix cache is invalidated: cached KV was computed under
        the old weights and must never be matched by a new-version
        prompt.  Any fault (the ``weights.swap`` failpoint, a geometry
        mismatch) raises BEFORE state changes — the engine keeps serving
        the old version intact.  Returns the new version label."""
        if self._faults is not None:
            self._faults.fire(WEIGHTS_SWAP,
                              detail=str(version or model_id or ""))
        cfg = model.config
        if (cfg.num_attention_heads != self.H
                or cfg.num_key_value_heads != self.KV
                or cfg.head_dim != self.D
                or cfg.hidden_size != self.E
                or cfg.num_hidden_layers != self.L):
            raise ValueError(
                "load_weights: new model's geometry (heads/kv/head_dim/"
                "hidden/layers) must match the engine's — the compiled "
                "step programs bake the attention geometry; boot a fresh "
                "engine for a different architecture")
        new = self._extract_weights(model)   # raises before any mutation
        self._weights = new
        self.blocks.drop_cached()
        if model_id is not None:
            self.model_id = str(model_id)
        if version is not None:
            self.weights_version = str(version)
        elif model_id is not None:
            # a model swap without an explicit version still must not
            # keep the old label (metrics/parity would lie about what
            # generated the tokens)
            self.weights_version = str(model_id)
        return self.weights_version

    def _build_rope(self, cfg):
        d = cfg.head_dim
        inv = 1.0 / (cfg.rope_theta ** (np.arange(0, d, 2, dtype=np.float64) / d))
        t = np.arange(self.max_seq_len, dtype=np.float64)
        fr = np.outer(t, inv)
        # blha rope layout [2, Br=1, Smax, 1, D/2]; llama uses the
        # half-split (neox) rotation (models/llama.py apply_rotary_pos_emb)
        return jnp.asarray(
            np.stack([np.cos(fr), np.sin(fr)])[:, None, :, None, :],
            jnp.float32)

    # ------------------------------------------------------- compiled step
    def _build_forward(self):
        cfg = self.cfg
        H, KV, D, E = self.H, self.KV, self.D, self.E
        eps = cfg.rms_norm_eps
        T, B, bs = self.T, self.B, self.bs

        def rms(x, w):
            xf = x.astype(jnp.float32)
            nrm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
            return (nrm * w.astype(jnp.float32)).astype(x.dtype)

        quant = self.cache_quant

        def trunk(weights, key_caches, value_caches, rope, token_ids,
                  enc, dec, now, cu, bt, mq, scales=None):
            # mq (static): padded per-sequence query length for the attention
            # compute — T for steps carrying prefill chunks, 1 for pure
            # decode steps (avoids T× padded-query attention waste).  The
            # trunk runs embed -> layers -> final rms and returns the FULL
            # hidden sequence: ``forward`` heads only each slot's last
            # packed token, the spec-verify program (ISSUE 19) heads every
            # draft position — one set of layer math, two consumers.
            hidden = weights["embed"][token_ids]  # [T, E]
            new_scales = []
            for li, lw in enumerate(weights["layers"]):
                h = rms(hidden, lw["ln1"])
                q = h @ lw["wq"]
                k = h @ lw["wk"]
                v = h @ lw["wv"]
                qkv = jnp.concatenate([q, k, v], axis=-1)
                sc = scales[li] if scales is not None else {}
                out, kc, vc, kq, vq, kd, vd = blha_attention(
                    qkv, key_caches[li], value_caches[li], enc, dec, now,
                    cu, bt, num_heads=H, kv_num_heads=KV, head_dim=D,
                    block_size=bs, max_q_len=mq, use_neox_style=True,
                    compute_dtype=hidden.dtype, rope_emb=rope,
                    cache_quant=quant if quant != "int8" else "dynamic",
                    cache_k_quant_scales=sc.get("kq"),
                    cache_v_quant_scales=sc.get("vq"),
                    cache_k_dequant_scales=sc.get("kd"),
                    cache_v_dequant_scales=sc.get("vd"))
                key_caches[li] = kc
                value_caches[li] = vc
                if scales is not None:
                    new_scales.append({"kq": kq, "vq": vq, "kd": kd, "vd": vd})
                hidden = hidden + out @ lw["wo"]
                h2 = rms(hidden, lw["ln2"])
                g = h2 @ lw["wg"]
                u = h2 @ lw["wu"]
                hidden = hidden + (jax.nn.silu(g) * u) @ lw["wd"]
            hidden = rms(hidden, weights["norm"])
            return hidden, key_caches, value_caches, new_scales

        def forward(weights, key_caches, value_caches, rope, token_ids,
                    enc, dec, now, cu, bt, mq, scales=None):
            hidden, kcs, vcs, new_scales = trunk(
                weights, key_caches, value_caches, rope, token_ids, enc,
                dec, now, cu, bt, mq, scales)
            # one logits row per batch slot: its LAST packed token
            rows = jnp.clip(cu[1:] - 1, 0, token_ids.shape[0] - 1)
            logits = hidden[rows] @ weights["head"]  # [B, V]
            return logits, kcs, vcs, new_scales

        return forward, trunk

    def _step_raw(self, weights, key_caches, value_caches, rope, token_ids,
                  enc, dec, now, cu, bt, mq, scales=None):
        """Undonated greedy step body (in-graph benching/scans keep the
        historical (nxt, kcs, vcs, scales) contract)."""
        logits, kcs, vcs, ns = self._forward(
            weights, key_caches, value_caches, rope, token_ids, enc, dec,
            now, cu, bt, mq, scales)
        nxt = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
        return nxt, kcs, vcs, ns

    def _build_step(self):
        fwd = self._forward
        with_probs = self.capture_sample_probs

        def step(weights, key_caches, value_caches, rope, token_ids,
                 enc, dec, now, cu, bt, temps, top_ks, top_ps, seeds,
                 sample_pos, mq, scales=None):
            logits, kcs, vcs, new_scales = fwd(
                weights, key_caches, value_caches, rope, token_ids, enc,
                dec, now, cu, bt, mq, scales)
            nxt, logprob, probs = _sample_tokens(
                logits, temps, top_ks, top_ps, seeds, sample_pos,
                return_probs=with_probs)
            return nxt, logprob, probs, kcs, vcs, new_scales

        return jax.jit(step, donate_argnums=(1, 2), static_argnames=("mq",))

    def _build_megastep(self):
        """K decode iterations inside one compiled ``lax.scan``: the
        pure-decode megastep program.  Per-row masking implements early
        exit — a row whose sequence finishes (EOS / budget) freezes its
        carry (token, cache position, sample index), so every later
        iteration re-feeds the same token at the same position and
        rewrites the SAME KV bits (deterministic fn of token, position,
        weights), while its sampled outputs are marked invalid and
        dropped on the host.  Rows with ``now=0`` (empty batch slots)
        never write at all.  Two ISSUE 16 carry threads: ``dl`` is the
        per-row deadline budget in ITERATIONS (a row freezes the moment
        it hits 0 — zero-token overshoot, checked in-graph as data, no
        clock in the compiled body), and ``scales`` carries the int8
        KV-quant per-(slot, kv-head) scale pytree — enc=0 decode rows
        pass the values through blha untouched, but quantize writes /
        dequantize reads with them, so ``cache_quant='int8'`` rides the
        same scan instead of keeping a per-token path."""
        fwd = self._forward
        B = self.B
        with_probs = self.capture_sample_probs

        def mega(weights, key_caches, value_caches, rope, toks, dec, now,
                 cu, occ_idx, bt, active, remaining, dl, eos, temps,
                 top_ks, top_ps, seeds, sample_pos, scales, K):
            enc = jnp.zeros((B,), jnp.int32)

            def body(carry, _):
                (toks, kcs, vcs, dec, active, remaining, sample_pos, dl,
                 scales) = carry
                packed = toks[occ_idx]    # slot-order -> packed layout
                logits, kcs, vcs, ns = fwd(weights, kcs, vcs, rope, packed,
                                           enc, dec, now, cu, bt, 1, scales)
                scales = ns if scales is not None else None
                nxt, lps, probs = _sample_tokens(
                    logits, temps, top_ks, top_ps, seeds, sample_pos,
                    return_probs=with_probs)
                # a row is ALIVE while unfinished and inside its deadline
                # budget; deadline-frozen rows stay active host-side (the
                # control plane finalizes the typed shed at harvest) but
                # emit nothing and advance nothing in-graph
                alive = active & (dl > 0)
                valid = alive
                fin = alive & ((nxt == eos) | (remaining <= 1))
                adv = alive & jnp.logical_not(fin)
                # freeze finished/frozen rows: token/position/sample-index
                # only advance while the row stays alive
                toks = jnp.where(adv, nxt, toks)
                dec = dec + adv.astype(jnp.int32)
                remaining = remaining - alive.astype(jnp.int32)
                sample_pos = sample_pos + alive.astype(jnp.int32)
                dl = dl - alive.astype(jnp.int32)
                active = active & jnp.logical_not(fin)
                return ((toks, kcs, vcs, dec, active, remaining,
                         sample_pos, dl, scales), (nxt, valid, lps, probs))

            carry0 = (toks, key_caches, value_caches, dec, active,
                      remaining, sample_pos, dl, scales)
            carry, (toks_o, valid_o, lps_o, probs_o) = jax.lax.scan(
                body, carry0, None, length=K)
            return (carry[1], carry[2], carry[8], toks_o, valid_o, lps_o,
                    probs_o)

        return jax.jit(mega, static_argnames=("K",), donate_argnums=(1, 2))

    def _build_mixed_megastep(self):
        """K MIXED-PHASE iterations inside one compiled ``lax.scan``:
        each iteration processes, per row, either ONE decode token or ONE
        prompt chunk of up to ``block_size`` tokens — so the megastep
        stays armed while prompts are still prefilling and open-loop
        admission never degrades decode back to per-token host stepping.

        Prompt chunks are pure data: the host stages a per-row prompt
        window ``prompt_buf[b] = prompt[pp0_b : pp0_b + K*block_size]``
        (zero-padded) and the scan slices the next chunk at offset
        ``pp - pp0`` from the ``prefill_pos`` carry.  Each iteration the
        per-row token counts are EXACT-packed into the [token_budget]
        buffer with an in-graph cumsum + scatter, so the forward's
        last-packed-token logits extraction (``cu[1:] - 1``) works
        unchanged; the attention runs with ``mq=block_size``.  No shape
        depends on which rows are prefilling — no recompile axes beyond
        the existing static K.

        Carry per row: next decode token, KV caches, ``cached`` (tokens
        written to KV = the blha ``dec`` argument, identical bookkeeping
        for both phases), ``pp`` (prefill position), active/remaining/
        sample-index masks, and the ``dl`` deadline iteration budget
        (same zero-overshoot freeze as the pure-decode scan — prefill
        chunks burn budget too).  A row emits a token only on decode
        iterations and on the iteration that FINISHES its prefill (the
        chunk's last packed token produces the first sampled token).
        int8 is excluded here by the scheduler: dynamic quant scales
        freeze at one-shot prefill, which chunking would violate."""
        fwd = self._forward
        B, T, C = self.B, self.T, self.pc
        with_probs = self.capture_sample_probs

        def mixed(weights, key_caches, value_caches, rope, toks, cached,
                  pp, pp0, plen, prompt_buf, bt, active, remaining, dl,
                  eos, temps, top_ks, top_ps, seeds, sample_pos, K):
            enc = jnp.zeros((B,), jnp.int32)

            def chunk_at(row, start):
                return jax.lax.dynamic_slice(row, (start,), (C,))

            def body(carry, _):
                (toks, kcs, vcs, cached, pp, active, remaining,
                 sample_pos, dl) = carry
                alive = active & (dl > 0)
                prefilling = pp < plen
                n_pre = jnp.minimum(plen - pp, C)
                now_t = jnp.where(
                    alive, jnp.where(prefilling, n_pre, 1), 0
                ).astype(jnp.int32)
                cu = jnp.concatenate(
                    [jnp.zeros((1,), jnp.int32),
                     jnp.cumsum(now_t).astype(jnp.int32)])
                # per-row tokens this iteration [B, C]: the next prompt
                # chunk for prefilling rows, the carried token at column
                # 0 for decode rows
                chunk = jax.vmap(chunk_at)(prompt_buf, pp - pp0)
                dec_row = jnp.zeros((B, C), jnp.int32).at[:, 0].set(toks)
                row_toks = jnp.where(prefilling[:, None], chunk, dec_row)
                # exact-pack into the [T] buffer (scatter; OOB -> drop):
                # slot b's tokens land at cu[b] .. cu[b]+now_t[b]-1, so
                # the packed layout is identical to the single-step path
                j = jnp.arange(C, dtype=jnp.int32)[None, :]
                flat = jnp.where(j < now_t[:, None], cu[:-1][:, None] + j,
                                 T)
                buf = jnp.zeros((T,), jnp.int32).at[flat.reshape(-1)].set(
                    row_toks.reshape(-1), mode="drop")
                logits, kcs, vcs, _ = fwd(weights, kcs, vcs, rope, buf,
                                          enc, cached, now_t, cu, bt, C,
                                          None)
                nxt, lps, probs = _sample_tokens(
                    logits, temps, top_ks, top_ps, seeds, sample_pos,
                    return_probs=with_probs)
                # a row emits on decode iterations and on the iteration
                # whose chunk finishes the prompt (its last packed token
                # is the prompt's last token -> first sampled token)
                finishing = prefilling & (pp + n_pre >= plen)
                emits = alive & (jnp.logical_not(prefilling) | finishing)
                fin = emits & ((nxt == eos) | (remaining <= 1))
                adv = emits & jnp.logical_not(fin)
                toks = jnp.where(adv, nxt, toks)
                cached = cached + now_t
                pp = pp + jnp.where(alive & prefilling, n_pre, 0)
                remaining = remaining - emits.astype(jnp.int32)
                sample_pos = sample_pos + emits.astype(jnp.int32)
                dl = dl - alive.astype(jnp.int32)
                active = active & jnp.logical_not(fin)
                return ((toks, kcs, vcs, cached, pp, active, remaining,
                         sample_pos, dl), (nxt, emits, lps, probs))

            carry0 = (toks, key_caches, value_caches, cached, pp, active,
                      remaining, sample_pos, dl)
            carry, (toks_o, emits_o, lps_o, probs_o) = jax.lax.scan(
                body, carry0, None, length=K)
            return (carry[1], carry[2], carry[4], toks_o, emits_o, lps_o,
                    probs_o)

        return jax.jit(mixed, static_argnames=("K",),
                       donate_argnums=(1, 2))

    def _build_spec_verify(self):
        """Score all ``spec_k + 1`` positions of every row's
        ``[last_token, draft_0 .. draft_{d-1}]`` feed in ONE batched
        forward and redraw each position with the EXACT key stream the
        non-spec path would use (greedy rows argmax; sampled rows
        ``categorical(fold_in(PRNGKey(seed), spos + j))`` over the same
        renormalized post-top-k/top-p q(x)).  Because the engine's redraw
        is deterministic, the Leviathan accept rule collapses to prefix
        matching: position j accepts iff its redraw EQUALS the draft, so
        the committed tokens are simply the redraw matrix's first
        ``accepted + 1`` columns — spec-on is token-identical to spec-off
        by construction, greedy and seeded.

        KV rewind is free, by the same argument the megastep scan uses
        to freeze finished rows: draft tokens write KV speculatively at
        ``dec .. dec+d``, the host advances ``dec`` only by the COMMITTED
        count, and a cache write is a deterministic function of (token,
        position, weights) — so accepted positions hold exactly the bits
        a non-spec feed would write, while rejected positions are
        overwritten by the next feed before any attention read reaches
        them (blha attends only up to the declared ``dec + now``).
        Prefix publishing never exposes stale bits either: it covers
        only committed-history-minus-last-token full blocks.

        The packed buffer is its OWN shape, [B * (spec_k+1)] — the trunk
        does not bake a packed length, and ``mq = spec_k + 1`` is the
        multi-token decode-extend case the mixed scan already exercises.
        int8 KV-quant is excluded by the scheduler (same dynamic-scale
        one-shot contract that excludes it from chunked prefill)."""
        trunk = self._trunk
        B, sk = self.B, self.spec_k
        Kp1 = sk + 1
        with_probs = self.capture_sample_probs

        def spec_verify(weights, key_caches, value_caches, rope,
                        token_ids, dec, now, cu, bt, dlen, draft, temps,
                        top_ks, top_ps, seeds, spos):
            enc = jnp.zeros((B,), jnp.int32)
            hidden, kcs, vcs, _ = trunk(
                weights, key_caches, value_caches, rope, token_ids, enc,
                dec, now, cu, bt, Kp1, None)
            # per-slot per-position logits rows: position j of slot b is
            # packed token cu[b] + j; rows whose draft is shorter than
            # spec_k clamp to their last fed token (masked out of the
            # accept below, so the garbage never commits)
            j = jnp.arange(Kp1, dtype=jnp.int32)[None, :]
            idx = jnp.clip(cu[:-1][:, None] + jnp.minimum(j, dlen[:, None]),
                           0, token_ids.shape[0] - 1)
            lg = (hidden[idx.reshape(-1)] @ weights["head"]).reshape(
                B, Kp1, -1)
            # redraw every position under the non-spec key stream (the
            # sample index advances by exactly one per position; Kp1 is
            # a small static constant, so a host loop over positions
            # keeps _sample_tokens' all-greedy cond a real cond)
            nxts, lpss, prbs = [], [], []
            for jj in range(Kp1):
                n_j, l_j, p_j = _sample_tokens(
                    lg[:, jj], temps, top_ks, top_ps, seeds, spos + jj,
                    return_probs=with_probs)
                nxts.append(n_j)
                lpss.append(l_j)
                if p_j is not None:
                    prbs.append(p_j)
            nxt = jnp.stack(nxts, axis=1)                    # [B, Kp1]
            lps = jnp.stack(lpss, axis=1)                    # [B, Kp1]
            probs = jnp.stack(prbs, axis=1) if prbs else None
            # accepted = longest draft prefix the redraw reproduces
            jk = jnp.arange(sk, dtype=jnp.int32)[None, :]
            match = (nxt[:, :sk] == draft) & (jk < dlen[:, None])
            acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                          axis=1).astype(jnp.int32)
            return kcs, vcs, nxt, lps, probs, acc

        return jax.jit(spec_verify, donate_argnums=(1, 2))

    # ------------------------------------------------------------- serving
    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    eos_token_id: Optional[int] = None,
                    sampling=None, sample_offset: int = 0,
                    trace: Optional[Dict] = None,
                    deadline_s: Optional[float] = None) -> int:
        """Queue one request.  ``sampling`` is a :class:`SamplingParams`
        (or its dict wire form; None = greedy argmax).  ``sample_offset``
        is the sample index of the first NEW token — a resumed request
        (prompt+generated re-prefilled after preemption/failover) passes
        the number of tokens already sampled so the seeded key stream
        continues exactly where it stopped.  ``deadline_s`` (seconds
        from now, this engine's clock) arms the IN-GRAPH deadline
        budget: megastep launches convert the remaining time into a scan
        iteration budget and the row freezes in-graph the moment it is
        spent — zero tokens of overshoot once a per-iteration estimate
        exists.  The engine only ever FREEZES on deadline; the typed
        shed (DEADLINE_EXCEEDED) stays the control plane's job — an
        engine driven standalone with an expired deadline will hit
        ``run()``'s max_steps loudly rather than silently dropping the
        request."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if sample_offset < 0:
            raise ValueError("sample_offset must be >= 0")
        total = len(prompt) + max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(f"prompt+max_new_tokens={total} exceeds "
                             f"max_seq_len={self.max_seq_len}")
        if self.cache_quant == "int8" and len(prompt) > self.T:
            # dynamic per-sequence scales are frozen by the (one-shot)
            # prefill — chunked prefills would quantize chunks under
            # different scales than the final dequant (the reference's
            # dynamic cache-quant mode has the same one-shot contract)
            raise ValueError(
                f"cache_quant='int8' needs the prompt ({len(prompt)} tokens) "
                f"to prefill in one step (token_budget={self.T}); raise the "
                "budget or use the unquantized cache")
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(ServingRequest(
            rid, prompt, max_new_tokens, eos_token_id,
            sampling=SamplingParams.coerce(sampling),
            sample_offset=int(sample_offset),
            trace=dict(trace) if trace else None,
            deadline_t=(self._clock() + float(deadline_s)
                        if deadline_s is not None else None)))
        return rid

    def _match_cached_prefix(self, prompt: List[int]):
        """Longest run of consecutive full prompt blocks whose chain
        hashes are content-addressable in the pool ->
        ``[(block_id, hash), ...]``."""
        matched = []
        parent = None
        for i in range(len(prompt) // self.bs):
            parent = prefix_block_hash(
                parent, prompt[i * self.bs:(i + 1) * self.bs])
            b = self.blocks.lookup(parent)
            if b is None:
                break
            matched.append((b, parent))
        return matched

    def _copy_block(self, src: int, dst: int):
        """Device-side copy of one pool block across every layer's K and V
        cache (the copy-on-write fork: the writer gets a private copy, the
        shared original stays read-only for its other owners)."""
        if self._cow_fn is None:
            if "cow" not in self._programs:
                def cow(kcs, vcs, s, d):
                    kcs = [kc.at[d].set(kc[s]) for kc in kcs]
                    vcs = [vc.at[d].set(vc[s]) for vc in vcs]
                    return kcs, vcs
                # s/d are data, not static: one compiled copy program total
                self._programs["cow"] = jax.jit(cow, donate_argnums=(0, 1))
            self._cow_fn = self._programs["cow"]
        self.key_caches, self.value_caches = self._cow_fn(
            self.key_caches, self.value_caches,
            jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32))

    def _try_admit(self):
        while self._queue and self._free_slots:
            req = self._queue[0]
            prompt = req.prompt
            need = (len(prompt) + req.max_new_tokens + self.bs - 1) // self.bs
            matched = (self._match_cached_prefix(prompt)
                       if self.prefix_cache_enabled else [])
            m = len(matched)
            # a fully-cached block-aligned prompt still needs ≥ 1 token of
            # real prefill (no compute = no logits for the first sampled
            # token): keep the whole match, but the final token re-feeds
            # into the LAST matched block — which is shared/read-only, so
            # that one block is copy-on-write-forked below
            full_match = m > 0 and m * self.bs == len(prompt)
            n_shared = m - 1 if full_match else m
            need_fresh = need - n_shared
            # pin the match first: the matched blocks may be sitting in the
            # reuse LRU, and allocating the tail could otherwise evict them
            for b, _ in matched:
                self.blocks.fork(b)
            if not self.blocks.can_allocate(need_fresh):
                self.blocks.free([b for b, _ in matched])  # unpin
                break  # head-of-line waits for retirements
            self._queue.pop(0)
            fresh = self.blocks.allocate(need_fresh)
            if full_match:
                # COW fork of the last matched block: the re-fed final
                # prompt token rewrites its own KV slot (same values) in a
                # private copy, never in the shared original
                cow_src = matched[-1][0]
                self._copy_block(cow_src, fresh[0])
                self.blocks.free([cow_src])   # drop the pin on the original
                req.blocks = [b for b, _ in matched[:-1]] + fresh
            else:
                req.blocks = [b for b, _ in matched] + fresh
            req.prefill_pos = min(m * self.bs, len(prompt) - 1)
            req.cached_prefix_tokens = req.prefill_pos
            if self.prefix_cache_enabled:
                self.prefix_hit_blocks += m
                self.prefix_miss_blocks += len(prompt) // self.bs - m
            req.slot = self._free_slots.pop()
            row = np.full((self.P,), -1, np.int32)
            row[:need] = req.blocks
            self.block_tables[req.slot] = row
            self._active[req.rid] = req

    def _publish_prefix(self, req: ServingRequest):
        """Make the request's full KV blocks content-addressable before
        they are freed, so the next request sharing the token prefix skips
        their prefill.  Only positions whose KV is actually WRITTEN count:
        the newest sampled token is fed (and cached) one step later, so it
        is excluded."""
        toks = req.prompt[:req.prefill_pos] + req.generated
        if req.generated:
            toks = toks[:-1]
        parent = None
        for i in range(len(toks) // self.bs):
            parent = prefix_block_hash(
                parent, toks[i * self.bs:(i + 1) * self.bs])
            self.blocks.publish(req.blocks[i], parent)

    def _release(self, req: ServingRequest):
        """Return a running request's blocks and batch slot to the pools
        (shared by retirement and mid-flight eviction).  With the prefix
        cache on, full blocks are published first: ``free`` then parks
        them reusable in the LRU instead of hard-freeing.  Idempotent:
        a deadline-frozen row is released at megastep harvest (ISSUE 19
        satellite) while staying in ``_active`` for the control plane's
        typed shed, so the later ``evict``/retire re-releases it."""
        if req.slot < 0:
            return
        if self.prefix_cache_enabled and req.blocks:
            self._publish_prefix(req)
        self.blocks.free(req.blocks)
        req.blocks = []
        self.block_tables[req.slot] = -1
        self._free_slots.append(req.slot)
        req.slot = -1

    def _retire(self, req: ServingRequest):
        req.done = True
        self._release(req)
        del self._active[req.rid]
        self._finished[req.rid] = list(req.generated)

    def evict(self, rid: int) -> ServingRequest:
        """Remove a queued or running request mid-flight (recompute
        preemption / cancellation hook for the control plane).

        Frees the request's blocks and batch slot immediately and returns
        the request object — ``prompt`` and ``generated`` are intact, so
        the caller can re-queue it with ``prompt + generated`` as the new
        prefill and get the identical greedy continuation.  ``prefill_pos``
        is reset; with the prefix cache on, the evicted request's full KV
        blocks are published before release, so a resume finds its own
        prefix cached and the recompute is nearly free (only the partial
        tail block and anything evicted under pool pressure re-prefills)."""
        req = self._active.get(rid)
        if req is not None:
            del self._active[rid]
            self._release(req)
            req.prefill_pos = 0
            return req
        for i, q in enumerate(self._queue):
            if q.rid == rid:
                return self._queue.pop(i)
        raise KeyError(f"no queued or active request with rid={rid}")

    def state_summary(self) -> Dict:
        """Host-side scheduling state, cheap and device-sync-free — the ONE
        probe shared by the fleet layer's heartbeat, the remote-replica
        state mirror, and the autoscaler (inference/fleet.py), so health
        checking and scaling decisions read the same numbers."""
        nb = self.blocks.num_blocks
        return {
            "queued": [(q.rid, len(q.prompt), q.max_new_tokens)
                       for q in self._queue],
            "active": {rid: len(r.blocks) for rid, r in self._active.items()},
            "free_slots": len(self._free_slots),
            "blocks_free": self.blocks.num_free,
            "blocks_total": nb,
            "queue_depth": len(self._queue),
            "num_active": len(self._active),
            "pool_utilization": (1.0 - self.blocks.num_free / nb) if nb else 0.0,
            # weight-swap attribution (ISSUE 18): the fleet mirror and
            # tenant routing read these off the same state reply
            "weights_version": self.weights_version,
            "model_id": self.model_id,
            # prefix-cache summary: the hash list is bounded by the pool
            # size (tens of entries), cheap enough to piggyback on every
            # RPC reply — the frontend's prefix-affinity routing matches
            # prompt hashes against it without an extra round trip
            "prefix_cache": {
                "enabled": self.prefix_cache_enabled,
                "hashes": sorted(self.blocks.cached_hashes())
                if self.prefix_cache_enabled else [],
                "cached_blocks": self.blocks.num_cached,
                "hit_blocks": self.prefix_hit_blocks,
                "miss_blocks": self.prefix_miss_blocks,
                "evictions": self.blocks.evictions,
            },
            # megastep decode counters (monotone; workers fold the deltas
            # into their registries, the frontend folds for in-process
            # engines) + the configured K for observability
            "megastep": {
                "k": self.megastep_k,
                "megasteps": self.megasteps,
                "tokens": self.megastep_tokens,
                "mixed": self.megasteps_mixed,
                "prefill_chunks": self.prefill_chunks,
            },
            # speculative-decode counters (ISSUE 19; same monotone
            # delta-fold contract as the megastep block above)
            "spec": {
                "k": self.spec_k,
                "accepted": self.spec_accepted_tokens,
                "drafted": self.spec_draft_tokens,
                "verify_forwards": self.spec_verify_forwards,
            },
            # cumulative host seconds per step phase — megastep cost
            # attribution without a profiler (ISSUE 15 satellite)
            "phase_seconds": dict(self.phase_seconds),
        }

    def pop_trace_events(self) -> List[Dict]:
        """Drain span events recorded by this engine's flight recorder
        since the last call (empty when tracing is off).  In-process
        frontends drain this directly; a worker host drains it into the
        ``_w_step`` reply so the frontend can graft engine-side spans
        (prefill done, megastep boundaries) onto the fleet-wide tree."""
        if self.trace_recorder is None:
            return []
        return self.trace_recorder.drain()

    def pop_finished(self) -> Dict[int, List[int]]:
        """Drain and return requests retired since the last call,
        {rid: generated tokens}.  The control plane harvests completions
        with this between ``step()`` calls; note it drains the same record
        ``run()`` returns, so mix the two styles per-engine, not both."""
        out = self._finished
        self._finished = {}
        return out

    def pop_token_logprobs(self) -> Dict[int, List[float]]:
        """Drain per-token logprobs recorded since the last call for
        requests with ``SamplingParams.logprobs=True`` — aligned 1:1 with
        the token lists ``step()`` emitted over the same window.  The
        control plane harvests this next to the emitted tokens; greedy
        default requests never appear here."""
        out = self._emitted_logprobs
        self._emitted_logprobs = {}
        return out

    def pop_sample_probs(self) -> Dict[int, List[np.ndarray]]:
        """Drain the renormalized post-top-k/top-p distributions each
        emitted token was drawn from (``capture_sample_probs=True``
        engines only) — {rid: [float32 [V], ...]} aligned 1:1 with the
        token lists ``step()`` emitted over the same window; greedy rows
        report a one-hot at the argmax.  This is the q(x) a speculative-
        decode verifier scores draft tokens against (ROADMAP item 2);
        harvested exactly like ``pop_token_logprobs``.  NB a
        ``ServingFrontend`` driving this engine drains (and discards)
        the buffer every step — it has no per-token consumer for [V]
        arrays and must not leak them — so verifiers harvest by driving
        the engine directly."""
        out = self._emitted_sample_probs
        self._emitted_sample_probs = {}
        return out

    def reap_orphans(self) -> int:
        """Evict EVERY queued and active request and drop any unharvested
        finished/logprob state; returns how many sequences were reaped.

        The crash-recovery hook (ISSUE 11): a restarted frontend
        reattaching to a still-live engine/worker must not leave the dead
        frontend's sequences decoding unobserved forever — recovery reaps
        them and re-admits from the journal (with the prefix cache on,
        the reaped requests' full blocks were published on eviction, so
        the re-prefill largely hits cache)."""
        rids = [q.rid for q in self._queue] + list(self._active)
        for rid in rids:
            self.evict(rid)
        self._finished.clear()
        self._emitted_logprobs.clear()
        self._emitted_sample_probs.clear()
        return len(rids)

    @staticmethod
    def _fill_sampling(req: ServingRequest, slot: int, temps, top_ks,
                       top_ps, seeds, spos):
        """Marshal one request's sampling params into the per-slot host
        arrays — the ONE fill both the single-step and megastep paths
        use, so a new knob cannot reach one program and not the other."""
        sp = req.sampling
        temps[slot] = sp.temperature
        top_ks[slot] = sp.top_k
        top_ps[slot] = sp.top_p
        seeds[slot] = sp.seed
        spos[slot] = req.sample_offset + len(req.generated)

    def step(self) -> Dict[int, List[int]]:
        """One engine iteration: schedule -> compiled step(s) -> retire.
        Returns tokens appended this step, {rid: [tok, ...]}.

        ARMING: whenever any scheduled row is decoding (and
        ``megastep_k > 1``), up to ``megastep_k`` iterations run inside
        ONE compiled ``lax.scan`` — the pure-decode scan when every row
        is decoding (int8 included; its scales ride the carry), the
        MIXED scan when prefilling rows share the batch (each iteration
        feeds those rows one block-size prompt chunk as data).  The
        returned lists then carry up to K tokens per request and the
        host — admission included — only observes the engine at megastep
        boundaries.  Prefill-only batches (plus int8 one-shot prefill
        and ``megastep_k=1``) run the single-step program."""
        t0 = self._clock()
        self._try_admit()
        if not self._active:
            self.phase_seconds["schedule"] += self._clock() - t0
            return {}
        if self._faults is not None:
            from .faults import prompt_signature

            # detail carries each active request's prompt signature so a
            # poison spec (match="p<t0>-<t1>-...") fires exactly when its
            # request is scheduled — and keeps firing on whichever replica
            # the request is retried on (the resumed prefill keeps the
            # original prompt as its head)
            self._faults.fire(
                "engine.step",
                detail=" ".join(prompt_signature(r.prompt)
                                for r in self._active.values()))
        enc = np.zeros((self.B,), np.int32)
        dec = np.zeros((self.B,), np.int32)
        now = np.zeros((self.B,), np.int32)
        budget = self.T
        sched: List[tuple] = []  # (req, n_tokens, finishes_prefill)
        # decode first (latency), then fill with prefill chunks.  Rows
        # with slot < 0 are deadline-frozen and already released at a
        # megastep harvest — they stay in _active only until the control
        # plane finalizes the typed shed, and must never re-schedule.
        for req in self._active.values():
            if req.slot < 0:
                continue
            if not req.in_prefill and budget > 0:
                sched.append((req, 1, False))
                budget -= 1
        for req in self._active.values():
            if req.slot < 0:
                continue
            if req.in_prefill and budget > 0:
                need = len(req.prompt) - req.prefill_pos
                if self.cache_quant == "int8" and need > budget:
                    # int8 dynamic scales freeze at prefill: the prefill must
                    # land in ONE step, so wait for enough budget (bounded
                    # wait — decoding slots retire and free it)
                    continue
                n = min(need, budget)
                sched.append((req, n, req.prefill_pos + n >= len(req.prompt)))
                budget -= n
                if self._faults is not None:
                    from .faults import prompt_signature

                    # chunk-boundary failpoint, single-step path: fires
                    # before any device mutation, once per prompt chunk
                    self._faults.fire("engine.prefill_chunk",
                                      detail=prompt_signature(req.prompt))
        if not sched:
            self.phase_seconds["schedule"] += self._clock() - t0
            return {}
        # pure-decode steps run the tight [B]-token program (mq=1); steps
        # carrying prefill chunks run the [T]-token program (mq=T) — decide
        # first, allocate the one token buffer the program actually takes
        decode_only = all(not r.in_prefill for r, _, _ in sched)
        # SPECULATIVE arming (ISSUE 19): pure-decode batches on a
        # spec_k > 0 engine try n-gram drafting first; one verify
        # forward then commits accepted+1 tokens per row.  int8 is
        # excluded (speculative rewind would need scale rewind), and a
        # launch with NO non-empty draft falls through — the megastep
        # is strictly better when there is nothing to verify.
        if (decode_only and self.spec_k > 0 and self.cache_quant != "int8"
                and any(r.sampling.spec for r, _, _ in sched)):
            spec_rows = [r for r, _, _ in sched]
            drafts = self._draft(spec_rows)
            if any(drafts.values()):
                armed = True
                if self._faults is not None:
                    from .faults import prompt_signature
                    try:
                        self._faults.fire(
                            SPEC_VERIFY,
                            detail=" ".join(prompt_signature(r.prompt)
                                            for r in spec_rows))
                    except Exception:
                        # degrade contract: a verify fault falls this
                        # step back to the non-spec megastep/single-step
                        # path — token-identical, never a wrong token
                        armed = False
                if armed:
                    self.phase_seconds["schedule"] += self._clock() - t0
                    return self._spec_step(spec_rows, drafts)
        if (decode_only and self.megastep_k > 1
                and max(r.max_new_tokens - len(r.generated)
                        for r, _, _ in sched) > 1):
            self.phase_seconds["schedule"] += self._clock() - t0
            return self._megastep([s[0] for s in sched])
        # MIXED-PHASE arming (ISSUE 16): any decoding row + any prefilling
        # row -> run both phases inside one scan instead of falling back
        # to per-token host stepping.  int8 keeps one-shot prefill
        # (dynamic scales freeze at prefill, chunking would violate it);
        # bs > T cannot exact-pack a full chunk into the token buffer.
        if (self.megastep_k > 1 and self.cache_quant != "int8"
                and self.pc <= self.T and not decode_only
                and any(not r.in_prefill for r, _, _ in sched)):
            dec_rows = [r for r, _, _ in sched if not r.in_prefill]
            pre_rows = []
            budget_m = self.T - len(dec_rows)
            for r, _, _ in sched:
                if r.in_prefill:
                    # worst-case packed tokens this row adds to any one
                    # iteration: its first chunk (chunks only shrink)
                    cost = min(self.pc, len(r.prompt) - r.prefill_pos)
                    if cost <= budget_m:
                        pre_rows.append(r)
                        budget_m -= cost
            if pre_rows:
                self.phase_seconds["schedule"] += self._clock() - t0
                return self._megastep_mixed(dec_rows, pre_rows)
        tokens = np.zeros((self.B if decode_only else self.T,), np.int32)
        # stable slot order so cu_seqlens is monotone over batch rows
        sched.sort(key=lambda s: s[0].slot)
        cu = np.zeros((self.B + 1,), np.int32)
        temps = np.zeros((self.B,), np.float32)
        top_ks = np.zeros((self.B,), np.int32)
        top_ps = np.ones((self.B,), np.float32)
        seeds = np.zeros((self.B,), np.int32)
        spos = np.zeros((self.B,), np.int32)
        per_slot = {s[0].slot: s for s in sched}
        pos = 0
        for slot in range(self.B):
            cu[slot + 1] = pos
            if slot not in per_slot:
                continue
            req, n, _ = per_slot[slot]
            self._fill_sampling(req, slot, temps, top_ks, top_ps, seeds,
                                spos)
            if req.in_prefill:
                chunk = req.prompt[req.prefill_pos:req.prefill_pos + n]
                enc[slot] = n
                dec[slot] = req.prefill_pos
                self.prefill_tokens_computed += n
            else:
                chunk = [req.generated[-1] if req.generated
                         else req.prompt[-1]]
                # cached tokens = prompt + generated[:-1]; the latest sampled
                # token is only being fed (and cached) THIS step
                dec[slot] = req.context_len - 1
            now[slot] = n
            tokens[pos:pos + n] = chunk
            pos += n
            cu[slot + 1] = pos

        t1 = self._clock()
        self.phase_seconds["schedule"] += t1 - t0
        had_cache = self._step_fn._cache_size() if hasattr(self._step_fn, "_cache_size") else None
        nxt, lps, probs, self.key_caches, self.value_caches, new_scales = \
            self._step_fn(
                self._weights, self.key_caches, self.value_caches,
                self._rope, jnp.asarray(tokens), jnp.asarray(enc),
                jnp.asarray(dec), jnp.asarray(now), jnp.asarray(cu),
                jnp.asarray(self.block_tables), jnp.asarray(temps),
                jnp.asarray(top_ks), jnp.asarray(top_ps),
                jnp.asarray(seeds), jnp.asarray(spos),
                mq=1 if decode_only else self.T, scales=self.cache_scales)
        if self.cache_scales is not None:
            self.cache_scales = new_scales
        if had_cache is not None:
            self.compile_count += self._step_fn._cache_size() - had_cache
        nxt = np.asarray(nxt)
        lps = np.asarray(lps)
        probs = np.asarray(probs) if probs is not None else None
        t2 = self._clock()
        self.phase_seconds["execute"] += t2 - t1

        emitted: Dict[int, List[int]] = {}
        for req, n, finishes in sched:
            if req.in_prefill:
                req.prefill_pos += n
                req.chunks_fed += 1
                self.prefill_chunks += 1
                if self.trace_recorder is not None and req.trace is not None:
                    self.trace_recorder.record(
                        req.trace["trace"], req.trace["span"],
                        req.trace.get("parent"), "prefill_chunk",
                        rid=req.trace.get("rid"),
                        chunk=req.chunks_fed - 1, tokens=n)
                if not finishes:
                    continue  # mid-prompt chunk: sampled token is meaningless
                if self.trace_recorder is not None and req.trace is not None:
                    self.trace_recorder.record(
                        req.trace["trace"], req.trace["span"],
                        req.trace.get("parent"), "prefill",
                        rid=req.trace.get("rid"),
                        prompt_len=len(req.prompt))
            tok = int(nxt[req.slot])
            req.generated.append(tok)
            if req.sampling.logprobs:
                req.logprob_values.append(float(lps[req.slot]))
                self._emitted_logprobs.setdefault(req.rid, []).append(
                    float(lps[req.slot]))
            if probs is not None:
                # .copy(): probs[slot] is a view pinning the whole [B,V]
                # step array alive (the megastep path's fancy-indexing
                # already copies)
                self._emitted_sample_probs.setdefault(req.rid, []).append(
                    probs[req.slot].copy())
            emitted.setdefault(req.rid, []).append(tok)
            hit_eos = (req.eos_token_id is not None and tok == req.eos_token_id)
            if hit_eos or len(req.generated) >= req.max_new_tokens:
                self._retire(req)
        self.phase_seconds["harvest"] += self._clock() - t2
        return emitted

    def _deadline_budgets(self, by_slot: Dict[int, "ServingRequest"]
                          ) -> np.ndarray:
        """Per-slot deadline budgets in SCAN ITERATIONS, computed on the
        host at megastep launch so the compiled body checks deadlines as
        pure data (wall clock never enters a traced program).  A row with
        no deadline — or no per-iteration time estimate yet — gets an
        effectively infinite budget; ``floor((deadline_t - now) / tau)``
        otherwise, so a conservative (large) tau freezes EARLY: that
        costs throughput, never correctness, and overshoot past the
        deadline stays zero."""
        dl = np.full((self.B,), 2 ** 30, np.int32)
        tau = self._tau
        if tau is None or tau <= 0:
            return dl
        now = self._clock()
        for slot, req in by_slot.items():
            if req.deadline_t is not None:
                dl[slot] = max(0, int((req.deadline_t - now) / tau))
        return dl

    def _update_tau(self, execute_s: float, k: int, compiled: bool):
        """Fold one megastep's measured execute time into the EWMA
        per-iteration estimate (skipped when deadline_token_seconds was
        injected, and on compile launches — trace+compile time is not
        steady-state iteration cost)."""
        if self._tau_override or compiled or k <= 0 or execute_s <= 0:
            return
        x = execute_s / k
        self._tau = x if self._tau is None else 0.8 * self._tau + 0.2 * x

    def _free_frozen(self, reqs: List[ServingRequest], dl: np.ndarray,
                     k: int):
        """ISSUE 19 satellite (the r16 remain): a row whose in-graph
        deadline budget ran out inside this scan is FROZEN — it will
        never emit again, but it used to park its slot and blocks until
        the control plane's typed shed at some later boundary.  Free
        them at harvest instead: the request stays in ``_active`` (slot
        -1, never re-scheduled) so the DEADLINE_EXCEEDED shed still
        happens at the control plane, while the queue head admits into
        the freed slot THIS control step.  A launch budget ``dl <= k``
        means the scan drove it to 0; ``_release`` is idempotent, so
        the shed's ``evict`` re-release is safe."""
        freed = False
        for req in reqs:
            if not req.done and req.slot >= 0 and dl[req.slot] <= k:
                self._release(req)
                freed = True
        if freed:
            self._try_admit()

    def _draft(self, reqs: List[ServingRequest]) -> Dict[int, List[int]]:
        """Host-side n-gram drafts for one spec launch, {rid: [tok, ..]}.
        Per request: drafting reads ONLY its own ``prompt + generated``
        history, and the length is capped at ``min(spec_k, remaining-1)``
        so (a) speculative KV writes stay inside the allocated blocks
        and (b) a full accept commits at most ``remaining`` tokens — no
        budget overshoot to truncate.  A ``engine.spec_draft`` fault
        degrades that ROW to an empty draft: it rides the verify and
        commits exactly its one non-spec token."""
        drafts: Dict[int, List[int]] = {}
        for r in reqs:
            d: List[int] = []
            cap = min(self.spec_k, r.max_new_tokens - len(r.generated) - 1)
            if r.sampling.spec and cap > 0:
                try:
                    if self._faults is not None:
                        from .faults import prompt_signature
                        self._faults.fire(SPEC_DRAFT,
                                          detail=prompt_signature(r.prompt))
                    d = ngram_draft(r.prompt + r.generated, cap)
                except Exception:
                    d = []   # degrade: this row rides undrafted
            drafts[r.rid] = d
        return drafts

    def _spec_step(self, reqs: List[ServingRequest],
                   drafts: Dict[int, List[int]]) -> Dict[int, List[int]]:
        """ONE batched verify forward over ``[last_token] + draft`` per
        row: the compiled program (``_build_spec_verify``) redraws every
        position with the exact non-spec key stream and reports the
        accepted draft-prefix length; the host commits the redraw
        matrix's first ``accepted + 1`` columns (the redraw IS the
        committed token at every accepted position — see the program's
        docstring), truncating at EOS exactly like the non-spec harvest.
        Counters: ``spec_verify_forwards`` counts ROWS scored (a
        per-token forward-equivalent, so forwards ÷ committed tokens is
        exactly 1.0 when nothing accepts and < 1.0 iff speculation
        pays), ``spec_draft_tokens`` counts proposals,
        ``spec_accepted_tokens`` counts committed draft tokens."""
        t0 = self._clock()
        B, sk = self.B, self.spec_k
        Kp1 = sk + 1
        tokens = np.zeros((B * Kp1,), np.int32)
        dec = np.zeros((B,), np.int32)
        now = np.zeros((B,), np.int32)
        cu = np.zeros((B + 1,), np.int32)
        dlen = np.zeros((B,), np.int32)
        draft_a = np.zeros((B, sk), np.int32)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        seeds = np.zeros((B,), np.int32)
        spos = np.zeros((B,), np.int32)
        reqs = sorted(reqs, key=lambda r: r.slot)
        by_slot = {r.slot: r for r in reqs}
        pos = 0
        for slot in range(B):
            cu[slot + 1] = pos
            req = by_slot.get(slot)
            if req is None:
                continue
            d = drafts.get(req.rid, [])
            row = [req.generated[-1] if req.generated else req.prompt[-1]]
            row.extend(int(t) for t in d)
            tokens[pos:pos + len(row)] = row
            dec[slot] = req.context_len - 1
            now[slot] = len(row)
            dlen[slot] = len(d)
            draft_a[slot, :len(d)] = d
            self._fill_sampling(req, slot, temps, top_ks, top_ps, seeds,
                                spos)
            pos += len(row)
            cu[slot + 1] = pos
        t1 = self._clock()
        self.phase_seconds["schedule"] += t1 - t0
        if self._spec_fn is None:
            if "spec" not in self._programs:
                self._programs["spec"] = self._build_spec_verify()
            self._spec_fn = self._programs["spec"]
        had = (self._spec_fn._cache_size()
               if hasattr(self._spec_fn, "_cache_size") else None)
        kcs, vcs, nxt, lps, probs, acc = self._spec_fn(
            self._weights, self.key_caches, self.value_caches, self._rope,
            jnp.asarray(tokens), jnp.asarray(dec), jnp.asarray(now),
            jnp.asarray(cu), jnp.asarray(self.block_tables),
            jnp.asarray(dlen), jnp.asarray(draft_a), jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(top_ps), jnp.asarray(seeds),
            jnp.asarray(spos))
        self.key_caches, self.value_caches = kcs, vcs
        if had is not None:
            self.compile_count += self._spec_fn._cache_size() - had
        nxt = np.asarray(nxt)       # [B, spec_k+1] redraws
        lps = np.asarray(lps)
        probs = np.asarray(probs) if probs is not None else None
        acc = np.asarray(acc)       # [B] accepted draft-prefix lengths
        t2 = self._clock()
        self.phase_seconds["execute"] += t2 - t1

        emitted: Dict[int, List[int]] = {}
        for req in reqs:
            s = req.slot
            new = [int(t) for t in nxt[s, :int(acc[s]) + 1]]
            if req.eos_token_id is not None and req.eos_token_id in new:
                # the non-spec engine stops AT the EOS: accepted draft
                # tokens past it were never going to be generated
                new = new[:new.index(req.eos_token_id) + 1]
            d = int(dlen[s])
            req.generated.extend(new)
            if req.sampling.logprobs:
                row_lps = [float(v) for v in lps[s, :len(new)]]
                req.logprob_values.extend(row_lps)
                self._emitted_logprobs.setdefault(req.rid, []).extend(
                    row_lps)
            if probs is not None:
                self._emitted_sample_probs.setdefault(req.rid, []).extend(
                    probs[s, j].copy() for j in range(len(new)))
            emitted[req.rid] = new
            self.spec_verify_forwards += 1
            self.spec_draft_tokens += d
            self.spec_accepted_tokens += len(new) - 1
            if self.trace_recorder is not None and req.trace is not None:
                self.trace_recorder.record(
                    req.trace["trace"], req.trace["span"],
                    req.trace.get("parent"), "spec_verify",
                    rid=req.trace.get("rid"), drafted=d,
                    accepted=len(new) - 1, tokens=len(new))
            hit_eos = (req.eos_token_id is not None
                       and new[-1] == req.eos_token_id)
            if hit_eos or len(req.generated) >= req.max_new_tokens:
                self._retire(req)
        self.phase_seconds["harvest"] += self._clock() - t2
        return emitted

    def _megastep(self, reqs: List[ServingRequest]) -> Dict[int, List[int]]:
        """Run up to ``megastep_k`` decode iterations in one compiled
        scan over the scheduled (all-decoding) requests.  K rounds up to
        a power of two (bounded compile count: one program per distinct
        K) capped at ``megastep_k``; rows that finish inside the scan are
        masked in-graph and their trailing samples dropped here."""
        if self._faults is not None:
            from .faults import prompt_signature

            # same poison-routing contract as the engine.step site, on the
            # batched-decode path: chaos schedules arm this to cover the
            # one-RPC-per-K-tokens fleet plumbing
            self._faults.fire(
                "engine.megastep",
                detail=" ".join(prompt_signature(r.prompt) for r in reqs))
        t0 = self._clock()
        kmax = max(r.max_new_tokens - len(r.generated) for r in reqs)
        K = 1
        while K < min(self.megastep_k, kmax):
            K *= 2
        K = min(K, self.megastep_k)
        B = self.B
        toks = np.zeros((B,), np.int32)
        dec = np.zeros((B,), np.int32)
        now = np.zeros((B,), np.int32)
        occ_idx = np.zeros((B,), np.int32)
        cu = np.zeros((B + 1,), np.int32)
        active = np.zeros((B,), bool)
        remaining = np.zeros((B,), np.int32)
        eos = np.full((B,), -1, np.int32)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        seeds = np.zeros((B,), np.int32)
        spos = np.zeros((B,), np.int32)
        reqs = sorted(reqs, key=lambda r: r.slot)
        by_slot = {r.slot: r for r in reqs}
        pos = 0
        for slot in range(B):
            req = by_slot.get(slot)
            if req is not None:
                occ_idx[pos] = slot
                toks[slot] = (req.generated[-1] if req.generated
                              else req.prompt[-1])
                dec[slot] = req.context_len - 1
                now[slot] = 1
                active[slot] = True
                remaining[slot] = req.max_new_tokens - len(req.generated)
                if req.eos_token_id is not None:
                    eos[slot] = req.eos_token_id
                self._fill_sampling(req, slot, temps, top_ks, top_ps,
                                    seeds, spos)
                pos += 1
            cu[slot + 1] = pos
        dl = self._deadline_budgets(by_slot)
        t1 = self._clock()
        self.phase_seconds["schedule"] += t1 - t0
        if self._mega_fn is None:
            if "mega" not in self._programs:
                self._programs["mega"] = self._build_megastep()
            self._mega_fn = self._programs["mega"]
        had = (self._mega_fn._cache_size()
               if hasattr(self._mega_fn, "_cache_size") else None)
        kcs, vcs, new_scales, toks_o, valid_o, lps_o, probs_o = \
            self._mega_fn(
                self._weights, self.key_caches, self.value_caches,
                self._rope, jnp.asarray(toks), jnp.asarray(dec),
                jnp.asarray(now), jnp.asarray(cu), jnp.asarray(occ_idx),
                jnp.asarray(self.block_tables), jnp.asarray(active),
                jnp.asarray(remaining), jnp.asarray(dl), jnp.asarray(eos),
                jnp.asarray(temps), jnp.asarray(top_ks),
                jnp.asarray(top_ps), jnp.asarray(seeds),
                jnp.asarray(spos), self.cache_scales, K=K)
        self.key_caches, self.value_caches = kcs, vcs
        if self.cache_scales is not None:
            self.cache_scales = new_scales
        compiled = False
        if had is not None:
            grew = self._mega_fn._cache_size() - had
            self.compile_count += grew
            compiled = grew > 0
        toks_o = np.asarray(toks_o)       # [K, B]
        valid_o = np.asarray(valid_o)
        lps_o = np.asarray(lps_o)
        probs_o = np.asarray(probs_o) if probs_o is not None else None
        self.megasteps += 1
        t2 = self._clock()
        self.phase_seconds["execute"] += t2 - t1
        self._update_tau(t2 - t1, K, compiled)

        emitted: Dict[int, List[int]] = {}
        for req in reqs:
            s = req.slot
            col = valid_o[:, s]
            new = [int(t) for t in toks_o[:, s][col]]
            req.generated.extend(new)
            if req.sampling.logprobs:
                row_lps = [float(v) for v in lps_o[:, s][col]]
                req.logprob_values.extend(row_lps)
                self._emitted_logprobs.setdefault(req.rid, []).extend(row_lps)
            if probs_o is not None and new:
                self._emitted_sample_probs.setdefault(req.rid, []).extend(
                    probs_o[:, s][col])   # [n_valid, V]
            emitted[req.rid] = new
            self.megastep_tokens += len(new)
            if self.trace_recorder is not None and req.trace is not None:
                self.trace_recorder.record(
                    req.trace["trace"], req.trace["span"],
                    req.trace.get("parent"), "megastep",
                    rid=req.trace.get("rid"), tokens=len(new), k=K)
            hit_eos = (req.eos_token_id is not None and new
                       and new[-1] == req.eos_token_id)
            if hit_eos or len(req.generated) >= req.max_new_tokens:
                self._retire(req)
        self._free_frozen(reqs, dl, K)
        self.phase_seconds["harvest"] += self._clock() - t2
        return emitted

    def _megastep_mixed(self, dec_reqs: List[ServingRequest],
                        pre_reqs: List[ServingRequest]
                        ) -> Dict[int, List[int]]:
        """Run up to ``megastep_k`` MIXED-PHASE iterations in one
        compiled scan: ``dec_reqs`` decode one token per iteration while
        ``pre_reqs`` consume one block-size prompt chunk per iteration
        (then decode in place once their prompt completes).  The caller
        guarantees the worst-case packed-token total fits the [T]
        buffer.  Unlike the pure-decode scan (power-of-two K buckets),
        mixed launches ALWAYS run the full ``megastep_k`` bucket: one
        compiled mixed program per engine.  Mixed arms under live
        admission, so a tail-sized launch (every row near completion)
        would compile a second multi-second XLA program mid-traffic —
        far costlier than the masked tail iterations it saves."""
        reqs = dec_reqs + pre_reqs
        if self._faults is not None:
            from .faults import prompt_signature

            self._faults.fire(
                "engine.megastep",
                detail=" ".join(prompt_signature(r.prompt) for r in reqs))
            for r in pre_reqs:
                # chunk-boundary failpoint: fires BEFORE the compiled
                # call (a fault never leaves half-committed tokens), once
                # per prompt entering the scan chunked
                self._faults.fire("engine.prefill_chunk",
                                  detail=prompt_signature(r.prompt))
        t0 = self._clock()
        C = self.pc
        K = self.megastep_k
        B = self.B
        toks = np.zeros((B,), np.int32)
        cached = np.zeros((B,), np.int32)
        pp = np.zeros((B,), np.int32)
        pp0 = np.zeros((B,), np.int32)
        plen = np.zeros((B,), np.int32)
        prompt_buf = np.zeros((B, K * C), np.int32)
        active = np.zeros((B,), bool)
        remaining = np.zeros((B,), np.int32)
        eos = np.full((B,), -1, np.int32)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        seeds = np.zeros((B,), np.int32)
        spos = np.zeros((B,), np.int32)
        by_slot = {r.slot: r for r in reqs}
        for slot, req in by_slot.items():
            active[slot] = True
            remaining[slot] = req.max_new_tokens - len(req.generated)
            if req.eos_token_id is not None:
                eos[slot] = req.eos_token_id
            self._fill_sampling(req, slot, temps, top_ks, top_ps, seeds,
                                spos)
            if req.in_prefill:
                # the prompt window this scan can reach: K chunks of C
                pp[slot] = pp0[slot] = cached[slot] = req.prefill_pos
                plen[slot] = len(req.prompt)
                window = req.prompt[req.prefill_pos:
                                    req.prefill_pos + K * C]
                prompt_buf[slot, :len(window)] = window
            else:
                toks[slot] = (req.generated[-1] if req.generated
                              else req.prompt[-1])
                cached[slot] = req.context_len - 1
                # pp == plen marks the row as decoding from iteration 0
                pp[slot] = pp0[slot] = plen[slot] = len(req.prompt)
        dl = self._deadline_budgets(by_slot)
        t1 = self._clock()
        self.phase_seconds["schedule"] += t1 - t0
        if self._mixed_fn is None:
            if "mixed" not in self._programs:
                self._programs["mixed"] = self._build_mixed_megastep()
            self._mixed_fn = self._programs["mixed"]
        had = (self._mixed_fn._cache_size()
               if hasattr(self._mixed_fn, "_cache_size") else None)
        kcs, vcs, pp_f, toks_o, emits_o, lps_o, probs_o = self._mixed_fn(
            self._weights, self.key_caches, self.value_caches, self._rope,
            jnp.asarray(toks), jnp.asarray(cached), jnp.asarray(pp),
            jnp.asarray(pp0), jnp.asarray(plen), jnp.asarray(prompt_buf),
            jnp.asarray(self.block_tables), jnp.asarray(active),
            jnp.asarray(remaining), jnp.asarray(dl), jnp.asarray(eos),
            jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps),
            jnp.asarray(seeds), jnp.asarray(spos), K=K)
        self.key_caches, self.value_caches = kcs, vcs
        compiled = False
        if had is not None:
            grew = self._mixed_fn._cache_size() - had
            self.compile_count += grew
            compiled = grew > 0
        pp_f = np.asarray(pp_f)           # [B] final prefill positions
        toks_o = np.asarray(toks_o)       # [K, B]
        emits_o = np.asarray(emits_o)
        lps_o = np.asarray(lps_o)
        probs_o = np.asarray(probs_o) if probs_o is not None else None
        self.megasteps += 1
        self.megasteps_mixed += 1
        t2 = self._clock()
        self.phase_seconds["execute"] += t2 - t1
        self._update_tau(t2 - t1, K, compiled)

        emitted: Dict[int, List[int]] = {}
        for req in sorted(reqs, key=lambda r: r.slot):
            s = req.slot
            col = emits_o[:, s]
            new = [int(t) for t in toks_o[:, s][col]]
            fed = int(pp_f[s]) - req.prefill_pos
            if fed > 0:
                # reconstruct the chunk boundaries the scan crossed (all
                # full C except a completing tail) for counters + spans
                req.prefill_pos += fed
                self.prefill_tokens_computed += fed
                nch = -(-fed // C)
                for i in range(nch):
                    ntok = min(C, fed - i * C)
                    req.chunks_fed += 1
                    self.prefill_chunks += 1
                    if (self.trace_recorder is not None
                            and req.trace is not None):
                        self.trace_recorder.record(
                            req.trace["trace"], req.trace["span"],
                            req.trace.get("parent"), "prefill_chunk",
                            rid=req.trace.get("rid"),
                            chunk=req.chunks_fed - 1, tokens=ntok)
                if (not req.in_prefill and self.trace_recorder is not None
                        and req.trace is not None):
                    self.trace_recorder.record(
                        req.trace["trace"], req.trace["span"],
                        req.trace.get("parent"), "prefill",
                        rid=req.trace.get("rid"),
                        prompt_len=len(req.prompt))
            req.generated.extend(new)
            if req.sampling.logprobs:
                row_lps = [float(v) for v in lps_o[:, s][col]]
                req.logprob_values.extend(row_lps)
                self._emitted_logprobs.setdefault(req.rid, []).extend(
                    row_lps)
            if probs_o is not None and new:
                self._emitted_sample_probs.setdefault(req.rid, []).extend(
                    probs_o[:, s][col])   # [n_valid, V]
            emitted[req.rid] = new
            self.megastep_tokens += len(new)
            if self.trace_recorder is not None and req.trace is not None:
                self.trace_recorder.record(
                    req.trace["trace"], req.trace["span"],
                    req.trace.get("parent"), "megastep",
                    rid=req.trace.get("rid"), tokens=len(new), k=K)
            hit_eos = (req.eos_token_id is not None and new
                       and new[-1] == req.eos_token_id)
            if hit_eos or len(req.generated) >= req.max_new_tokens:
                self._retire(req)
        self._free_frozen(reqs, dl, K)
        self.phase_seconds["harvest"] += self._clock() - t2
        return emitted

    def run(self, max_steps: int = 10_000) -> Dict[int, List[int]]:
        """Drive until every queued/active request retires.

        Raises ``RuntimeError`` when ``max_steps`` is exhausted with
        requests still queued or active — a truncated run must not be
        mistaken for completion (the returned dict would silently miss
        the unfinished requests' tokens).
        """
        for _ in range(max_steps):
            if not self._queue and not self._active:
                break
            self.step()
            if self._queue and not self._active:
                self._try_admit()  # retirements this step freed capacity
            if self._queue and not self._active:
                # nothing running, everything free, and the queue head still
                # could not be admitted: it can NEVER fit (pool/slot capacity
                # too small) — fail loudly instead of spinning no-ops
                head = self._queue[0]
                need = (len(head.prompt) + head.max_new_tokens
                        + self.bs - 1) // self.bs
                raise RuntimeError(
                    f"request {head.rid} needs {need} cache blocks but the "
                    f"pool only has {self.blocks.num_blocks} total "
                    f"({self.blocks.num_free} free with nothing running) — "
                    "raise num_blocks/max_seq_len or shrink the request")
        if self._queue or self._active:
            raise RuntimeError(
                f"ServingEngine.run: max_steps={max_steps} exhausted with "
                f"{len(self._active)} active and {len(self._queue)} queued "
                "request(s) unfinished — raise max_steps (or drain with "
                "step() and read partial results from the request objects)")
        return dict(self._finished)

    @property
    def num_active(self) -> int:
        return len(self._active)

    @property
    def prefix_evictions(self) -> int:
        """Cached blocks dropped from the reuse LRU under allocation
        pressure (monotone; the control plane folds it into metrics)."""
        return self.blocks.evictions

    def cached_block_hashes(self) -> Set[str]:
        """Chain hashes content-addressable in this engine's pool right
        now — what prefix-affinity routing scores a prompt against
        (``fleet.RemoteReplica`` mirrors this from ``state_summary``)."""
        if not self.prefix_cache_enabled:
            return set()
        return self.blocks.cached_hashes()

    # ------------------------------------------------- block transfer
    # (kv_fabric.py: disaggregated prefill/decode moves KV between
    # engines as bit-exact payloads keyed by chain hash)

    def _check_transferable(self, op: str):
        if self.cache_quant == "int8":
            raise ValueError(
                f"{op} cannot be used with cache_quant='int8': the int8 "
                "cache dequantizes through per-(slot, kv-head) DYNAMIC "
                "scales frozen at each sequence's own prefill, so a "
                "block's uint8 payload is only meaningful under its "
                "writer's scales — another engine importing it would "
                "dequantize garbage. Disaggregated transfer requires the "
                "unquantized cache")

    def export_blocks_packed(self, hashes: Sequence[str]) -> Tuple[Dict,
                                                                   bytes]:
        """Bit-exact KV payload for a chain of published block hashes
        (parent-first order) as ONE contiguous packed buffer — the
        binary data-plane form (inference/blockwire.py, ISSUE 20).
        Stops at the first hash this pool no longer holds — a chain is
        only usable up to its first gap, so exporting past one would
        ship unmatchable blocks.  Returns ``(header, raw)``: a
        self-describing geometry header (``shape`` = ``[2, layers,
        nblocks, kv_heads, block_size, head_dim]``, K/V stacked over
        the engine's native per-block cache slice) plus the raw bytes
        of one batched device→host gather — a single jitted stacked
        gather + ONE ``np.asarray`` for the whole chain, not
        ``2 × layers × nblocks`` individual copies."""
        self._check_transferable("export_blocks_packed")
        held: List[str] = []
        ids: List[int] = []
        for h in hashes:
            b = self.blocks.lookup(h)
            if b is None:
                break
            held.append(h)
            ids.append(int(b))
        header = {"block_size": self.bs, "layers": self.L,
                  "kv_heads": self.KV, "head_dim": self.D,
                  "dtype": str(self.key_caches[0].dtype), "hashes": held,
                  "shape": [2, self.L, len(held), self.KV, self.bs, self.D]}
        if not held:
            return header, b""
        if "gather" not in self._programs:
            def gather(kcs, vcs, bids):
                k = jnp.stack([kc[bids] for kc in kcs])
                v = jnp.stack([vc[bids] for vc in vcs])
                return jnp.stack([k, v])   # [2, L, n, KV, bs, D]
            self._programs["gather"] = jax.jit(gather)
        packed = self._programs["gather"](self.key_caches,
                                          self.value_caches,
                                          jnp.asarray(ids, jnp.int32))
        return header, np.asarray(packed).tobytes()

    def export_blocks(self, hashes: Sequence[str]) -> Dict:
        """Bit-exact KV payload for a chain of published block hashes
        (parent-first order) in the dict form — the compatibility /
        frontend-relay fallback; ``export_blocks_packed`` is the data
        plane.  Both run the same single batched device→host gather
        (the per-block-per-layer ``np.asarray`` loop this replaced cost
        ``2 × layers × nblocks`` host round trips); the dict's arrays
        are host-side views into that one buffer."""
        header, raw = self.export_blocks_packed(hashes)
        blocks: Dict[str, Dict[str, list]] = {}
        held = header["hashes"]
        if held:
            arr = np.frombuffer(raw, dtype=_np_dtype(header["dtype"]))
            arr = arr.reshape(header["shape"])
            for i, h in enumerate(held):
                blocks[h] = {"k": [arr[0, li, i] for li in range(self.L)],
                             "v": [arr[1, li, i] for li in range(self.L)]}
        return {"block_size": self.bs, "layers": self.L, "kv_heads": self.KV,
                "head_dim": self.D, "dtype": header["dtype"],
                "blocks": blocks}

    def import_blocks(self, payload: Dict) -> int:
        """Install an ``export_blocks`` payload into this pool: allocate
        a block, write the bits on device, ``publish`` it under its
        chain hash while live, then ``free`` it — which parks it in the
        reuse LRU, content-addressable exactly like a locally-prefilled
        published block.  Already-cached hashes are skipped (first
        publisher wins); allocation pressure stops the import early
        (partial chains are still useful from the root).  Returns the
        number of blocks imported."""
        self._check_transferable("import_blocks")
        geom = (payload.get("block_size"), payload.get("layers"),
                payload.get("kv_heads"), payload.get("head_dim"),
                payload.get("dtype"))
        want = (self.bs, self.L, self.KV, self.D,
                str(self.key_caches[0].dtype))
        if geom != want:
            raise ValueError(
                f"import_blocks: payload geometry {geom} does not match "
                f"this engine's cache geometry {want} (block_size, layers, "
                "kv_heads, head_dim, dtype) — transfers require identical "
                "cache layouts")
        imported = 0
        for h, kv in payload.get("blocks", {}).items():
            if self.blocks.lookup(h) is not None:
                continue
            if not self.blocks.can_allocate(1):
                break
            (b,) = self.blocks.allocate(1)
            self._write_block(b, kv["k"], kv["v"])
            self.blocks.publish(b, h)
            self.blocks.free([b])   # park published: reusable, evictable
            imported += 1
        return imported

    def import_blocks_packed(self, header: Dict, raw: bytes) -> int:
        """Install an ``export_blocks_packed`` chain segment: validate
        the self-describing geometry header AND that the raw byte count
        matches what the geometry implies BEFORE touching the cache — a
        torn/truncated buffer is a typed ValueError, never a wrong or
        half-imported block — then allocate/write/publish/free exactly
        like :meth:`import_blocks`.  Returns the imported count."""
        self._check_transferable("import_blocks_packed")
        geom = (header.get("block_size"), header.get("layers"),
                header.get("kv_heads"), header.get("head_dim"),
                header.get("dtype"))
        want = (self.bs, self.L, self.KV, self.D,
                str(self.key_caches[0].dtype))
        if geom != want:
            raise ValueError(
                f"import_blocks_packed: payload geometry {geom} does not "
                f"match this engine's cache geometry {want} (block_size, "
                "layers, kv_heads, head_dim, dtype) — transfers require "
                "identical cache layouts")
        hashes = [str(h) for h in header.get("hashes") or ()]
        shape = [2, self.L, len(hashes), self.KV, self.bs, self.D]
        if list(header.get("shape") or ()) != shape:
            raise ValueError(
                f"import_blocks_packed: header shape "
                f"{header.get('shape')} does not match the geometry-"
                f"implied {shape}")
        dt = _np_dtype(str(header["dtype"]))
        expect = 1
        for dim in shape:
            expect *= int(dim)
        expect *= dt.itemsize
        if len(raw) != expect:
            raise ValueError(
                f"import_blocks_packed: payload is {len(raw)} bytes but "
                f"the geometry implies {expect} — truncated or padded "
                "buffer rejected whole")
        arr = np.frombuffer(raw, dtype=dt).reshape(shape)
        imported = 0
        for i, h in enumerate(hashes):
            if self.blocks.lookup(h) is not None:
                continue
            if not self.blocks.can_allocate(1):
                break
            (b,) = self.blocks.allocate(1)
            self._write_block(b, [arr[0, li, i] for li in range(self.L)],
                              [arr[1, li, i] for li in range(self.L)])
            self.blocks.publish(b, h)
            self.blocks.free([b])
            imported += 1
        return imported

    def pull_blocks(self, peer_endpoint: str, hashes: Sequence[str], *,
                    epoch: Optional[int] = None,
                    timeout: float = 60.0) -> Tuple[int, int]:
        """Pull a chain segment DIRECTLY off a peer's data-plane
        listener (inference/blockwire.py) and import it — the
        destination side of the one-hop transfer; the frontend only
        ever orchestrates this with directory-sized control messages.
        Returns ``(blocks_imported, payload_bytes)``.  Raises
        ``StaleEpoch`` when the peer fenced the handshake, ``WireError``
        for transport faults — callers degrade to the frontend relay."""
        from .blockwire import default_pool

        header, raw = default_pool().pull(peer_endpoint, list(hashes),
                                          epoch=epoch, timeout=timeout)
        return self.import_blocks_packed(header, raw), len(raw)

    def _write_block(self, dst: int, ks: Sequence[np.ndarray],
                     vs: Sequence[np.ndarray]):
        """Device-side write of one imported block across every layer's
        K and V cache (same shape of program as the COW copy: the block
        id is data, so one compiled write program serves every import)."""
        if self._put_fn is None:
            if "put" not in self._programs:
                def put(kcs, vcs, d, ks, vs):
                    kcs = [kc.at[d].set(k) for kc, k in zip(kcs, ks)]
                    vcs = [vc.at[d].set(v) for vc, v in zip(vcs, vs)]
                    return kcs, vcs
                self._programs["put"] = jax.jit(put, donate_argnums=(0, 1))
            self._put_fn = self._programs["put"]
        self.key_caches, self.value_caches = self._put_fn(
            self.key_caches, self.value_caches, jnp.asarray(dst, jnp.int32),
            [jnp.asarray(k) for k in ks], [jnp.asarray(v) for v in vs])
