"""Cross-host serving fleet: remote ServingEngine replicas behind the
SLO-aware frontend (the layer ROADMAP's "single-host-per-replica" open
item asks for; reference analogs: fleet elastic's worker registry +
health loop for membership, Orca/vLLM's scheduler-over-engine-workers
split for the data plane).

Three pieces, layered on four existing subsystems:

* **Worker side** — ``tools/serving_worker.py`` builds a ``ServingEngine``
  in its own process (spawnable on another host), registers with the
  launch KV master, and serves the module-level ``_w_*`` handlers below
  over the ``distributed/rpc`` HTTP stack.  One ``_w_health`` probe
  returns engine scheduling state + a metrics snapshot — heartbeat,
  state mirror, and autoscaler all share it instead of growing three
  code paths.
* **``RemoteReplica``** — duck-types the exact ServingEngine surface
  ``ServingFrontend`` drives (``add_request``/``step``/``evict``/
  ``pop_finished`` + the capacity/scheduling attrs), proxying each call
  over RPC with a per-call timeout.  Every RPC piggybacks the worker's
  post-call ``state_summary`` so the frontend's local mirror of queue/
  slots/blocks is exactly what an in-process engine would show — which
  is why routing, priority admission, deadlines, and recompute
  preemption work unchanged, and why a local and a remote fleet produce
  token-identical schedules.  With megastep decode (ISSUE 9) one step
  RPC returns up to ``megastep_k`` tokens per running sequence — the
  engine batches K decode iterations into one compiled scan, so the
  per-token HTTP round trips that capped the r8 fleet rung collapse by
  K; host-side control (deadlines, cancel, autoscaling signals) runs at
  those megastep boundaries.
* **``ServingFleet``** — spawns/attaches workers (parallel process
  launch + KV-registration wait), builds the ``ServingFrontend`` over
  the ``RemoteReplica`` set, and adds what only the fleet layer can see:
  heartbeat health-checking (a silent worker — hung step, SIGKILL, or
  idle-but-dead — fails over via ``ServingFrontend.fail_replica``, which
  re-queues its in-flight requests from host-side state), drain-based
  scale-down (stop admitting, finish in-flight, deregister), and
  fleet-wide metrics aggregation (``ServingMetrics.merge`` +
  ``prometheus_text_fleet`` with a ``replica`` label).  The shared
  admission state (per-class token budgets, queue caps) already lives in
  the frontend, so it holds fleet-wide by construction.
* **``FleetAutoscaler``** — queue-depth / SLO-pressure policy object:
  scales up when queued work per accepting replica (or p95 TTFT) stays
  above target, drains the most idle worker after enough consecutive
  idle observations, never leaves fewer than ``min_workers`` accepting.
  Scale-up is NON-BLOCKING: ``spawn_worker_async`` launches the process
  and a background thread absorbs the ~10 s jax-import + compile boot;
  the step loop keeps serving and attaches the replica once its health
  probe answers (workers still booting count toward ``max_workers``).

Failure contract: any RPC fault (connection refused after SIGKILL, typed
``RpcTimeout`` from a hung worker) surfaces either in ``step()`` —
caught by the frontend's existing failover — or in the heartbeat, which
routes through the same path.  Requests are re-queued from frontend-side
state (prompt + tokens harvested so far) and finish on survivors with
greedy-identical tokens; nothing is dropped.  Fault containment on top
(ISSUE 7): heartbeat probes are idempotent and retry transient transport
faults with backoff before declaring a worker dead (data-plane ``step``
stays fail-fast into failover); spawn failures and early worker deaths
feed a ``RespawnCircuitBreaker`` the autoscaler consults before every
scale-up, so a crash-looping worker config backs off exponentially
(jittered) instead of paying a doomed ~10 s boot per observation;
``spawn_errors`` is a bounded ring; and the ``fleet.spawn`` /
``fleet.heartbeat`` failpoints (``inference/faults.py``) let the chaos
soak drive all of it deterministically.

Durability (ISSUE 11): workers are separate processes, so they OUTLIVE
a crashed frontend.  Arm the frontend with a write-ahead journal
(``frontend_kwargs={"journal": path}``); after a frontend death, a new
process reattaches — ``discover_workers(master_endpoint)`` lists the
still-registered workers (external KV master), ``RemoteReplica`` each,
and ``ServingFrontend.recover(journal, replicas)`` reaps the orphaned
sequences worker-side (``_w_reap_orphans`` RPC; eviction publishes
their full KV blocks, so the recovered re-prefill largely hits the
prefix cache on the same worker) and re-admits from the journal.

High availability (ISSUE 12): every control RPC handler below is
FENCED — it carries the calling frontend's epoch (``epoch=`` kwarg,
stamped by ``RemoteReplica.set_epoch``), the worker's ``EpochFence``
remembers the highest epoch its process has ever seen, and an older
epoch raises the typed ``StaleEpoch`` before the handler touches the
engine.  This is what makes standby failover safe against zombies: a
SIGSTOP'd frontend resumed after its lease expired cannot know it was
deposed, but its first write lands as a typed rejection instead of
corrupting streams the new incarnation owns.  ``_w_health`` stays
unfenced (read-only; standbys watch through it) and reports the
highest epoch seen.  ``connect_workers`` is the standby's replica
factory: discovery + liveness probe + stale-entry pruning.

Scope note: each worker is still one host / one engine; true multi-host
TPU meshes *per replica* (a sharded engine spanning hosts) remain open.
"""
from __future__ import annotations

import errno
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .control_plane import ServingFrontend
from .faults import FaultInjector, RespawnCircuitBreaker, register_failpoint
from .ha import EpochFence, StaleEpoch
from .metrics import (MEGASTEP_COUNTERS, SPEC_COUNTERS, ServingMetrics,
                      fold_counter_deltas, fold_prefix_counters)

__all__ = ["RemoteReplica", "ServingFleet", "FleetAutoscaler",
           "AutoscalePolicy", "WarmPool", "init_worker", "discover_workers",
           "connect_workers", "worker_roles"]

# warm-worker pool lifecycle edges (ISSUE 18): an attach pulled from the
# pool, and a refill launched to top it back up — both chaos-drivable
POOL_ATTACH = register_failpoint("pool.attach")
POOL_REFILL = register_failpoint("pool.refill")


def discover_workers(master_endpoint: str,
                     exclude: Sequence[str] = ("fleet-frontend",)
                     ) -> List[str]:
    """Worker names currently registered with the launch KV master —
    what a RESTARTED frontend reattaches to (ISSUE 11 recovery): workers
    are separate processes and outlive a crashed frontend, so recovery
    is ``[RemoteReplica(n) for n in discover_workers(ep)]`` (after
    ``rpc.init_rpc``/``refresh_workers``) handed to
    ``ServingFrontend.recover``, which reaps their orphaned sequences
    and re-admits from the journal.  Requires an external KV master (the
    production shape); a fleet that started its OWN in-process KVServer
    took the registry down with it.

    ``exclude`` filters non-worker registrations: the rpc layer
    registers EVERY participant under ``/rpc/workers/``, including
    frontends (``ServingFleet`` registers as ``fleet-frontend``; HA
    incarnations and standbys register under their own names) — and a
    SIGKILLed frontend never deregisters, so its stale entry would
    otherwise come back as a bogus "worker".  Any name CONTAINING
    ``"frontend"`` is excluded by construction (the repo's frontend
    naming convention — never name a worker that), plus the exact names
    in ``exclude``; pass the recovering process's own rpc name too if
    it does not match the convention."""
    from ..distributed.launch.master import KVClient

    kv = KVClient(master_endpoint)
    entries = kv.get_prefix("/rpc/workers/")
    names = (k.rsplit("/", 1)[-1] for k in entries)
    drop = set(exclude)
    # warm-pool workers (ISSUE 18) are registered and serving-ready but
    # deliberately UNATTACHED — a recovering frontend must not adopt them
    # as serving replicas (the owning fleet's pool claims them); the
    # ``/serving/warm/<name>`` marker is deleted at claim time, so a
    # claimed-and-attached warm worker IS discoverable like any other
    drop |= {k.rsplit("/", 1)[-1] for k in kv.get_prefix("/serving/warm/")}
    return sorted(n for n in names if n not in drop and "frontend" not in n)


def worker_roles(master_endpoint: str) -> Dict[str, str]:
    """Disaggregation role labels registered alongside the workers
    (``/serving/roles/<name>``, written by tools/serving_worker.py right
    after its rpc registration).  The label ALSO rides every health
    reply (``RemoteReplica.role``), so this registry view exists for the
    paths that must know a worker's role without probing it — takeover
    planning, operator tooling — and as the KV-side source of truth a
    recovered frontend can audit its rebuilt fleet against."""
    from ..distributed.launch.master import KVClient

    entries = KVClient(master_endpoint).get_prefix("/serving/roles/")
    return {k.rsplit("/", 1)[-1]: v for k, v in entries.items()}


def worker_wires(master_endpoint: str) -> Dict[str, str]:
    """Data-plane listener endpoints registered alongside the workers
    (``/serving/wire/<name>``, written by tools/serving_worker.py right
    next to its role label; ISSUE 20).  Like the role label, the
    endpoint ALSO rides every health reply
    (``RemoteReplica.wire_endpoint``) — this registry view is for
    operator tooling and KV-side audits."""
    from ..distributed.launch.master import KVClient

    entries = KVClient(master_endpoint).get_prefix("/serving/wire/")
    return {k.rsplit("/", 1)[-1]: v for k, v in entries.items()}


# the only probe failures that PROVE nothing is listening at the
# advertised endpoint; every other OSError (reset, broken pipe) can come
# from a live worker's transient connection blip and must not prune
_DEAD_ENDPOINT_ERRNOS = frozenset({
    errno.ECONNREFUSED, errno.EHOSTUNREACH, errno.ENETUNREACH,
    errno.EHOSTDOWN, errno.ENETDOWN})


def _is_dead_endpoint(e: OSError) -> bool:
    # urllib surfaces a refused connect as URLError(reason=
    # ConnectionRefusedError) with errno=None on the wrapper — check
    # the wrapped reason too
    for err in (e, getattr(e, "reason", None)):
        if isinstance(err, ConnectionRefusedError) \
                or getattr(err, "errno", None) in _DEAD_ENDPOINT_ERRNOS:
            return True
    return False


def connect_workers(master_endpoint: str,
                    exclude: Sequence[str] = ("fleet-frontend",),
                    rpc_timeout: float = 60.0,
                    prune_stale: bool = True,
                    probe_timeout_s: float = 5.0) -> List["RemoteReplica"]:
    """``discover_workers`` + a liveness probe: wrap every discovered
    name in a ``RemoteReplica`` (whose constructor round-trips the
    health RPC) and SKIP the ones that don't answer — a dead worker's
    stale KV entry (SIGKILLed between heartbeats, host gone) must not
    come back as a bogus replica in a recovered frontend.
    ``prune_stale`` deletes the dead entries from the registry so the
    next discovery is clean — but ONLY for probes that failed with a
    definitive dead-endpoint error (connection refused, no route): a
    probe that merely TIMED OUT may be a live worker mid-megastep or
    mid-XLA-compile, and one whose HANDLER raised (an armed
    ``health.probe`` failpoint, a transient engine error) answered over
    a healthy connection — registration is one-shot (``init_rpc``), so
    deleting either entry would delist a healthy worker forever.  Both
    are skipped this takeover and re-probed by the next discovery.
    ``probe_timeout_s`` bounds each liveness probe SEPARATELY from the
    replicas' data-plane ``rpc_timeout``: probes run sequentially, and a
    black-holed dead host (no RST, just silence) would otherwise burn
    the full step timeout per worker on the takeover path the lease TTL
    was tuned for.  Requires an rpc session (``rpc.init_rpc``);
    refreshes the routing table itself.  This is the
    ``replica_factory`` a ``StandbyFrontend`` should use."""
    from ..distributed import rpc
    from ..distributed.launch.master import KVClient

    rpc.refresh_workers()
    kv = KVClient(master_endpoint)
    # role-correct rebuild (disaggregation): the health reply carries the
    # worker's own role label; the KV registry entry backs it up so a
    # worker predating the label (or a probe that lost the field) still
    # lands in the right pool — a recovered frontend must never route
    # prefill passes to a decode-only worker or vice versa
    roles = {k.rsplit("/", 1)[-1]: v
             for k, v in kv.get_prefix("/serving/roles/").items()}
    out: List[RemoteReplica] = []
    for name in discover_workers(master_endpoint, exclude):
        try:
            rep = RemoteReplica(name, rpc_timeout=rpc_timeout,
                                probe_timeout=probe_timeout_s)
            if rep.role is None:
                rep.role = roles.get(name)
            out.append(rep)
        except rpc.RpcTimeout:
            continue           # live-but-slow ≠ stale: skip, never prune
        except OSError as e:
            # ...unless the error is REMOTE (rpc marks handler-raised
            # exceptions): a worker whose health handler raised an
            # OSError subclass — e.g. an armed health.probe failpoint of
            # kind timeout/drop — ANSWERED over a healthy connection
            if getattr(e, "_rpc_remote", False):
                continue
            # only DEFINITIVE dead-endpoint errnos may prune: a local
            # reset/broken-pipe is a transient blip (listener mid-
            # restart, full accept backlog) from a worker that is very
            # much alive — deleting its one-shot registration on that
            # would delist it forever
            if prune_stale and _is_dead_endpoint(e):
                kv.delete(f"/rpc/workers/{name}")
        # graft-lint: disable=typed-termination — liveness probe: the
        # worker ANSWERED (its handler raised), so it is alive and the
        # registry entry stays; the fault itself belongs to the caller
        # that eventually drives this worker, not to discovery
        except Exception:  # noqa: BLE001 — the worker ANSWERED (its
            continue       # handler raised): alive, keep the entry
    return out


class _BoundedErrors(OrderedDict):
    """Dict-shaped ring of the most recent errors: a crash-looping
    spawner must not grow ``ServingFleet.spawn_errors`` without bound.
    Oldest entries fall off past ``maxlen``; lookup/containment/iteration
    behave like the plain dict this replaces."""

    def __init__(self, maxlen: int = 32):
        super().__init__()
        self.maxlen = int(maxlen)

    def __setitem__(self, key, value):
        if key in self:
            del self[key]              # refresh recency
        super().__setitem__(key, value)
        while len(self) > self.maxlen:
            self.popitem(last=False)


# --------------------------------------------------------------------------
# worker side: process-global engine + module-level RPC handlers.  The rpc
# stack pickles functions BY REFERENCE (module + qualname), so these must be
# importable under the same path in the worker process.
# --------------------------------------------------------------------------
_WORKER: Dict[str, Any] = {
    "engine": None, "metrics": None, "stop": None, "name": None,
    "prefix_seen": (0, 0, 0), "mega_seen": (0, 0, 0, 0),
    "spec_seen": (0, 0, 0), "faults": None,
    "fence": EpochFence(), "role": None,
}


def init_worker(engine, name: str,
                stop: Optional[threading.Event] = None,
                metrics: Optional[ServingMetrics] = None,
                fault_injector: Optional[FaultInjector] = None,
                role: Optional[str] = None) -> threading.Event:
    """Install ``engine`` as this process's served replica (called by
    tools/serving_worker.py before ``rpc.init_rpc``).  Returns the stop
    event ``_w_shutdown`` sets.  ``fault_injector`` arms the worker-side
    failpoints (``health.probe`` here; the engine carries its own
    ``engine.step`` site) for chaos runs.  A fresh ``EpochFence`` is
    armed too: it lives for the worker PROCESS — frontends come and go
    across it (that is the whole point), each bumping the highest epoch
    seen with its first control RPC.  ``role`` labels the worker for
    disaggregated serving ('prefill' = prefill passes only, 'decode' =
    decode placement only, None = both); it rides the health reply (so
    ``RemoteReplica``/``connect_workers`` rebuild role-correct fleets on
    takeover) and is stamped onto the engine for in-process callers."""
    if "frontend" in name:
        # discover_workers/connect_workers drop any registration whose
        # name contains "frontend" (that's how stale frontend-generation
        # entries are excluded) — a worker registered under such a name
        # would serve fine but be invisible to every takeover: never
        # probed, never orphan-reaped, decoding unobserved forever
        raise ValueError(
            f"worker name {name!r} contains 'frontend', which recovery "
            "discovery excludes by construction — pick another name")
    _WORKER["engine"] = engine
    _WORKER["metrics"] = metrics if metrics is not None else ServingMetrics()
    _WORKER["stop"] = stop if stop is not None else threading.Event()
    _WORKER["name"] = name
    _WORKER["prefix_seen"] = (0, 0, 0)
    _WORKER["mega_seen"] = (0, 0, 0, 0)
    _WORKER["spec_seen"] = (0, 0, 0)
    _WORKER["faults"] = (fault_injector if fault_injector is not None
                         else FaultInjector.from_env())
    _WORKER["fence"] = EpochFence()
    if role is not None and role not in ("prefill", "decode"):
        raise ValueError(
            f"worker role must be 'prefill', 'decode' or None, got {role!r}")
    _WORKER["role"] = role
    engine.role = role
    return _WORKER["stop"]


def _engine():
    eng = _WORKER["engine"]
    if eng is None:
        raise RuntimeError("serving worker not initialised (init_worker)")
    return eng


def _fence(epoch, op: str):
    """Worker-side epoch fence (ISSUE 12), first line of every control
    RPC handler: the highest epoch this process has ever seen wins, and
    a call from an older one raises the typed ``StaleEpoch`` BEFORE the
    handler touches the engine — a zombie frontend's write lands as a
    typed rejection, never as duplicate token execution.  Unfenced
    (``epoch=None``) callers pass: fencing arms the moment any frontend
    carries an epoch.  Counted in the worker's ``fenced_rpcs_total``
    (the worker did the fencing, so the worker's registry — which the
    fleet scrape page exports — owns the count)."""
    try:
        _WORKER["fence"].check(epoch, op)
    except StaleEpoch:
        _WORKER["metrics"].inc("fenced_rpcs_total")
        raise


def _w_config() -> Dict:
    eng = _engine()
    return {
        "max_batch_size": eng.B, "token_budget": eng.T, "block_size": eng.bs,
        "max_seq_len": eng.max_seq_len, "num_blocks": eng.blocks.num_blocks,
        "cache_quant": eng.cache_quant, "pid": os.getpid(),
    }


def _w_add_request(prompt, max_new_tokens, eos_token_id=None,
                   sampling=None, sample_offset=0, epoch=None, trace=None,
                   deadline_s=None):
    _fence(epoch, "add_request")
    eng = _engine()
    # the trace wire context rides the RPC like epoch= (ISSUE 15): the
    # worker engine records its span events against the frontend's
    # attempt span, shipped back on the _w_step reply.  deadline_s is the
    # REMAINING deadline in seconds (relative, like the journal wire
    # form): the worker engine re-anchors it on its own clock and
    # freezes the row in-graph at the budget (ISSUE 16)
    rid = eng.add_request(prompt, max_new_tokens=max_new_tokens,
                          eos_token_id=eos_token_id, sampling=sampling,
                          sample_offset=sample_offset, trace=trace,
                          deadline_s=deadline_s)
    return rid, eng.state_summary()


def _w_step(epoch=None):
    """One engine step per RPC — which, with megastep decode (ISSUE 9),
    means up to ``megastep_k`` tokens per round trip: the per-token HTTP
    transport cost the r8 fleet rung identified collapses by K."""
    _fence(epoch, "step")
    eng = _engine()
    emitted = eng.step()
    finished = eng.pop_finished()
    lp_fn = getattr(eng, "pop_token_logprobs", None)
    logprobs = lp_fn() if lp_fn is not None else {}
    if getattr(eng, "capture_sample_probs", False):
        # same drain the frontend does for in-process engines: nothing
        # ships the [V]-sized distributions over RPC, so a capture-
        # enabled worker spec must not accumulate them forever
        eng.pop_sample_probs()
    m = _WORKER["metrics"]
    m.inc("engine_steps_total")
    n_tok = sum(len(t) for t in emitted.values())
    if n_tok:
        m.note_tokens(n_tok)
    st = eng.state_summary()
    m.set_gauge_peak("queue_depth", st["queue_depth"])
    m.set_gauge("running_requests", st["num_active"])
    m.set_gauge("blocks_capacity", st["blocks_total"])
    m.set_gauge("blocks_free", st["blocks_free"])
    m.set_gauge_peak("block_pool_utilization", st["pool_utilization"])
    ps = st.get("phase_seconds") or {}
    if ps:
        m.set_gauge("step_phase_schedule_seconds", ps.get("schedule", 0.0))
        m.set_gauge("step_phase_execute_seconds", ps.get("execute", 0.0))
        m.set_gauge("step_phase_harvest_seconds", ps.get("harvest", 0.0))
    # engine-level counters are monotone; fold the per-step deltas so
    # _w_reset_metrics windows stay correct
    pc = st.get("prefix_cache") or {}
    cur = (int(pc.get("hit_blocks", 0)), int(pc.get("miss_blocks", 0)),
           int(pc.get("evictions", 0)))
    _WORKER["prefix_seen"] = fold_prefix_counters(m, cur,
                                                  _WORKER["prefix_seen"])
    ms = st.get("megastep") or {}
    mcur = (int(ms.get("megasteps", 0)), int(ms.get("tokens", 0)),
            int(ms.get("mixed", 0)), int(ms.get("prefill_chunks", 0)))
    _WORKER["mega_seen"] = fold_counter_deltas(m, MEGASTEP_COUNTERS, mcur,
                                               _WORKER["mega_seen"])
    sp = st.get("spec") or {}
    scur = (int(sp.get("accepted", 0)), int(sp.get("drafted", 0)),
            int(sp.get("verify_forwards", 0)))
    _WORKER["spec_seen"] = fold_counter_deltas(m, SPEC_COUNTERS, scur,
                                               _WORKER["spec_seen"])
    m.inc("completed_total", len(finished))
    # span events the engine recorded this step (prefill done, megastep
    # boundaries) piggyback on the reply — the frontend grafts them onto
    # its fleet-wide trees (tracing disabled -> always [])
    pt_fn = getattr(eng, "pop_trace_events", None)
    traces = pt_fn() if pt_fn is not None else []
    return emitted, finished, st, logprobs, traces


def _w_pop_traces(epoch=None):
    """Drain the worker engine's buffered span events without stepping —
    the recovery-path drain: a takeover frontend pulls the spans a dead
    frontend never collected before it reaps.  Fenced like every control
    RPC (a zombie draining them would hide events from the successor)."""
    _fence(epoch, "pop_traces")
    eng = _engine()
    pt_fn = getattr(eng, "pop_trace_events", None)
    return pt_fn() if pt_fn is not None else []


def _w_evict(rid, epoch=None):
    _fence(epoch, "evict")
    eng = _engine()
    eng.evict(rid)
    return eng.state_summary()


def _w_reap_orphans(epoch=None):
    """Evict every queued/active sequence on this worker — the recovery
    hook (ISSUE 11) a RESTARTED frontend calls when it reattaches: the
    worker outlived the dead frontend, so whatever it is running belongs
    to nobody and would otherwise decode unobserved forever.  The
    recovered frontend re-admits the journaled requests afterwards (and
    with the prefix cache on, eviction published their full blocks, so
    the re-prefill largely hits cache on this same worker).

    With fencing armed this is the FIRST rpc of the new incarnation's
    epoch: the fence bumps here, so the dead/zombie frontend is locked
    out of this worker before recovery re-admits anything."""
    _fence(epoch, "reap_orphans")
    eng = _engine()
    n = eng.reap_orphans()
    _WORKER["metrics"].inc("orphans_reaped_total", n)
    return n, eng.state_summary()


def _w_export_blocks(hashes, epoch=None):
    """Bit-exact KV payload for a chain of published block hashes — the
    source side of the disaggregated prefill→decode transfer
    (inference/kv_fabric.py).  Fenced: a deposed frontend must not farm
    this worker's blocks out to replicas the current incarnation is not
    scheduling.  The payload is host numpy and ships over the pickle
    transport like any reply."""
    _fence(epoch, "export_blocks")
    return _engine().export_blocks(hashes)


def _w_import_blocks(payload, epoch=None):
    """Install a transferred KV payload into this worker's pool (the
    destination side of the disaggregated hop); returns the imported
    block count plus the post-import state summary so the frontend's
    mirror — including the prefix-hash set affinity routing reads —
    reflects the new content-addressable blocks immediately."""
    _fence(epoch, "import_blocks")
    eng = _engine()
    n = eng.import_blocks(payload)
    _WORKER["metrics"].inc("fabric_blocks_imported_total", n)
    return n, eng.state_summary()


def _w_pull_blocks(peer_endpoint, hashes, epoch=None):
    """Direct-wire transfer (ISSUE 20): THIS worker (the decode side)
    pulls a packed chain segment straight off ``peer_endpoint`` — the
    prefill worker's blockwire data-plane listener — and imports it.
    The frontend orchestrates with this directory-sized control RPC
    only; payload bytes take one hop instead of riding the pickle
    control channel through the frontend twice.  Fenced on BOTH ends:
    this RPC here, and the peer's listener fences the same epoch in
    the wire handshake before any payload bytes move.  Raises what the
    wire raised (typed WireError / StaleEpoch) — the frontend's fabric
    ladder owns the relay/recompute fallback."""
    _fence(epoch, "pull_blocks")
    eng = _engine()
    n, nbytes = eng.pull_blocks(str(peer_endpoint), list(hashes),
                                epoch=epoch)
    _WORKER["metrics"].inc("fabric_blocks_imported_total", n)
    _WORKER["metrics"].inc("fabric_wire_pulls_total")
    return n, int(nbytes), eng.state_summary()


def _w_health(include_samples: bool = False):
    """The one shared probe: heartbeat liveness, autoscaler load signals,
    and metrics aggregation all read this."""
    inj = _WORKER.get("faults")
    if inj is not None:
        # a probe that raises here travels back as an RPC error — exactly
        # the shape a wedged health handler produces
        inj.fire("health.probe", detail=str(_WORKER.get("name")))
    # deliberately UNFENCED (read-only): standbys watch workers through
    # this probe, and a deposed frontend's monitoring may keep scraping
    eng = _engine()
    return {
        "state": eng.state_summary(),
        "metrics": _WORKER["metrics"].snapshot(include_samples=include_samples),
        "config": _w_config(),
        "draining": False,  # drain state is frontend-side; kept for probes
        "name": _WORKER["name"],
        "epoch": _WORKER["fence"].highest,   # highest epoch ever seen
        "role": _WORKER.get("role"),         # disaggregation label
        # data-plane listener endpoint (ISSUE 20): rides the probe like
        # the role label so RemoteReplica/connect_workers rebuild
        # wire-capable fleets on takeover without a KV read
        "wire": getattr(eng, "wire_endpoint", None),
    }


def _w_reset_metrics(epoch=None):
    """Zero the worker's registry (benches call this after the warmup/
    compile phase so engine-level counters cover the same measured window
    as the frontend's).  Fenced: a zombie must not erase the counters —
    including ``fenced_rpcs_total`` itself — out from under the current
    incarnation."""
    _fence(epoch, "reset_metrics")
    _WORKER["metrics"].reset()
    return True


def _w_swap_weights(model_kwargs, seed, version=None, model_id=None,
                    bfloat16=False, epoch=None):
    """Rebuild a seeded model from spec kwargs in THIS process and load
    it into the serving engine (ISSUE 18 rolling weight swap).  The wire
    form is the worker-spec recipe, not weight tensors: every replica of
    a version builds bit-identical weights from (seed, config), exactly
    like boot, so a fleet-wide swap ships a few hundred bytes of JSON
    per worker instead of the checkpoint.  Fenced — a deposed frontend
    must not roll weights under the current incarnation — and the
    engine's own ``load_weights`` fires the ``weights.swap`` failpoint
    and validates geometry BEFORE mutating, so a faulted swap leaves the
    old version serving.  Returns (installed version, state summary)."""
    _fence(epoch, "swap_weights")
    eng = _engine()
    import paddle_tpu as P
    from ..models import LlamaConfig, LlamaForCausalLM

    P.seed(int(seed))
    model = LlamaForCausalLM(LlamaConfig(**(model_kwargs or {})))
    if bfloat16:
        model.bfloat16()
    model.eval()
    v = eng.load_weights(model, version=version, model_id=model_id)
    _WORKER["metrics"].inc("weight_swaps_total")
    return v, eng.state_summary()


def _w_shutdown(epoch=None):
    # fenced: a deposed frontend must not shut down workers the current
    # incarnation is serving with
    _fence(epoch, "shutdown")
    _WORKER["stop"].set()
    return True


# --------------------------------------------------------------------------
# frontend side
# --------------------------------------------------------------------------
class _QView:
    """Mirror of one queued-but-unadmitted remote request; exposes the two
    things frontend headroom math reads (``len(prompt)``,
    ``max_new_tokens``)."""

    __slots__ = ("rid", "prompt", "max_new_tokens")

    def __init__(self, rid: int, prompt_len: int, max_new_tokens: int):
        self.rid = rid
        self.prompt = range(prompt_len)
        self.max_new_tokens = max_new_tokens


class _ActiveView:
    """Mirror of one running remote request; ``len(blocks)`` feeds the
    preemption victim-sizing math."""

    __slots__ = ("blocks",)

    def __init__(self, num_blocks: int):
        self.blocks = range(num_blocks)


class _RemoteBlockView:
    """BlockManager facade over the worker's last-synced pool state."""

    def __init__(self, num_blocks: int, num_free: int):
        self.num_blocks = num_blocks
        self.num_free = num_free


class RemoteReplica:
    """ServingEngine-shaped proxy for an engine living in a worker process.

    The frontend schedules against a local mirror of the worker's host-side
    state (queue, free slots, free blocks, per-request block counts); every
    RPC returns the worker's post-call ``state_summary`` and the mirror is
    replaced wholesale, so it is exactly as fresh as an in-process engine's
    own attributes between frontend operations.  All calls carry
    ``rpc_timeout`` — a hung worker raises ``RpcTimeout`` into the
    frontend's failover path instead of freezing the step loop."""

    # the worker folds its engine's prefix counters into its own registry
    # (_w_step), which the fleet scrape/merge paths already collect — the
    # frontend's gauge sampler must not fold the mirror a second time
    prefix_counters_self_reported = True

    # the worker counts each fence into its own scraped registry, so
    # the frontend must not count it again (see ServingFrontend._fenced)
    fences_self_reported = True

    def __init__(self, worker_name: str, rpc_timeout: float = 60.0,
                 probe_timeout: Optional[float] = None):
        from ..distributed import rpc

        self._rpc = rpc
        self.worker = worker_name
        self.rpc_timeout = float(rpc_timeout)
        # fencing epoch (ISSUE 12): stamped by the owning frontend via
        # set_epoch and carried on every control RPC; the worker rejects
        # older epochs with the typed StaleEpoch.  None = unfenced.
        self._epoch: Optional[int] = None
        # the constructor's liveness probe may use a SHORTER deadline
        # than data-plane calls: discovery over N workers probes them
        # sequentially, and a black-holed host would otherwise burn the
        # full step timeout per dead worker on the takeover path
        t = (float(probe_timeout) if probe_timeout is not None
             else self.rpc_timeout)
        h = self._rpc.rpc_sync(self.worker, _w_health, timeout=t)
        cfg = h["config"]
        # disaggregation role label (init_worker): rides every health
        # reply so a takeover frontend rebuilds a role-correct fleet
        self.role = h.get("role")
        # data-plane listener endpoint (ISSUE 20): the fabric ladder
        # reads this off the SOURCE replica to decide the wire rung
        self.wire_endpoint = h.get("wire")
        self.B = int(cfg["max_batch_size"])
        self.T = int(cfg["token_budget"])
        self.bs = int(cfg["block_size"])
        self.max_seq_len = int(cfg["max_seq_len"])
        self.cache_quant = cfg["cache_quant"]
        self.pid = cfg["pid"]
        self.blocks = _RemoteBlockView(int(cfg["num_blocks"]),
                                       int(cfg["num_blocks"]))
        self._queue: List[_QView] = []
        self._active: Dict[int, _ActiveView] = {}
        self._free_slots: List[int] = list(range(self.B))
        self._finished: Dict[int, List[int]] = {}
        self._logprobs: Dict[int, List[float]] = {}
        self._trace_events: List[Dict] = []  # worker spans off _w_step replies
        self._pending_step = None
        self._apply_state(h["state"])

    # ------------------------------------------------------------ plumbing
    def _call(self, fn, *args, **kwargs):
        return self._rpc.rpc_sync(self.worker, fn, args=args,
                                  kwargs=kwargs, timeout=self.rpc_timeout)

    def set_epoch(self, epoch: int):
        """Stamp the caller epoch every subsequent control RPC carries
        (the frontend propagates its epoch here at attach/recover)."""
        self._epoch = int(epoch)

    def _apply_state(self, st: Dict):
        self._queue = [_QView(rid, pl, mn) for rid, pl, mn in st["queued"]]
        self._active = {rid: _ActiveView(nb)
                        for rid, nb in st["active"].items()}
        self._free_slots = list(range(st["free_slots"]))
        self.blocks.num_free = int(st["blocks_free"])
        # prefix-cache mirror: the hash summary feeds frontend-side
        # prefix-affinity routing, the counters feed _sample_gauges —
        # exactly the attributes an in-process engine exposes
        pc = st.get("prefix_cache") or {}
        self.prefix_cache_enabled = bool(pc.get("enabled"))
        self._prefix_hashes = frozenset(pc.get("hashes") or ())
        self.prefix_hit_blocks = int(pc.get("hit_blocks", 0))
        self.prefix_miss_blocks = int(pc.get("miss_blocks", 0))
        self.prefix_evictions = int(pc.get("evictions", 0))
        # megastep mirror (the worker folds these into its own registry;
        # prefix_counters_self_reported keeps the frontend from double-
        # counting the mirror, same as the prefix counters)
        ms = st.get("megastep") or {}
        self.megastep_k = int(ms.get("k", 1))
        self.megasteps = int(ms.get("megasteps", 0))
        self.megastep_tokens = int(ms.get("tokens", 0))
        self.megasteps_mixed = int(ms.get("mixed", 0))
        self.prefill_chunks = int(ms.get("prefill_chunks", 0))
        # speculative-decode mirror (ISSUE 19): same self-reported fold
        # contract as the megastep counters above
        sp = st.get("spec") or {}
        self.spec_k = int(sp.get("k", 0))
        self.spec_accepted_tokens = int(sp.get("accepted", 0))
        self.spec_draft_tokens = int(sp.get("drafted", 0))
        self.spec_verify_forwards = int(sp.get("verify_forwards", 0))
        # per-phase step-time mirror (the worker sets the gauges in its
        # own registry too; the frontend sums mirrors like the block
        # counts above)
        self.phase_seconds = dict(st.get("phase_seconds") or {})
        # weights identity mirror (ISSUE 18): version label for metrics/
        # trace attribution and model id for tenant-affine routing — the
        # frontend reads these exactly like an in-process engine's attrs
        self.weights_version = st.get("weights_version", "v0")
        self.model_id = st.get("model_id", "default")

    def cached_block_hashes(self):
        """Last-synced mirror of the worker engine's content-addressable
        block hashes (piggybacked on every RPC reply)."""
        return self._prefix_hashes

    # ----------------------------------------------- ServingEngine surface
    @property
    def num_active(self) -> int:
        return len(self._active)

    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    eos_token_id: Optional[int] = None,
                    sampling=None, sample_offset: int = 0,
                    trace: Optional[Dict] = None,
                    deadline_s: Optional[float] = None) -> int:
        prompt = [int(t) for t in prompt_ids]
        if sampling is not None and not isinstance(sampling, dict):
            # ship the dict wire form (no class pickling across versions)
            sampling = sampling.to_wire()
        rid, st = self._call(_w_add_request, prompt, int(max_new_tokens),
                             eos_token_id, sampling, int(sample_offset),
                             epoch=self._epoch, trace=trace,
                             deadline_s=deadline_s)
        self._apply_state(st)
        return rid

    def begin_step(self):
        """Issue the step RPC without waiting (the frontend calls this on
        every replica first, then collects via ``step()`` — concurrent
        replicas overlap their engine steps instead of serializing the
        HTTP round trips)."""
        if self._pending_step is None:
            self._pending_step = self._rpc.rpc_async(
                self.worker, _w_step, kwargs={"epoch": self._epoch},
                timeout=self.rpc_timeout)

    def step(self) -> Dict[int, List[int]]:
        fut = self._pending_step
        self._pending_step = None
        if fut is not None:
            emitted, finished, st, lps, traces = fut.result()
        else:
            emitted, finished, st, lps, traces = self._call(
                _w_step, epoch=self._epoch)
        self._apply_state(st)
        self._finished.update(finished)
        for rid, vals in lps.items():
            self._logprobs.setdefault(rid, []).extend(vals)
        if traces:
            self._trace_events.extend(traces)
        return emitted

    def pop_trace_events(self) -> List[Dict]:
        """Local drain of the worker span events buffered off ``_w_step``
        replies — same shape as ``ServingEngine.pop_trace_events``, and
        crucially NOT an RPC (the frontend drains it after stepping, so
        a dead worker cannot fault the trace harvest)."""
        out = self._trace_events
        self._trace_events = []
        return out

    def pop_remote_traces(self) -> List[Dict]:
        """``_w_pop_traces`` RPC: pull span events the worker recorded
        but never shipped (no step happened, or the previous frontend
        died before collecting) — the recovery/takeover drain."""
        evs = self._call(_w_pop_traces, epoch=self._epoch)
        if evs:
            self._trace_events.extend(evs)
        return self.pop_trace_events()

    def pop_finished(self) -> Dict[int, List[int]]:
        out = self._finished
        self._finished = {}
        return out

    def pop_token_logprobs(self) -> Dict[int, List[float]]:
        out = self._logprobs
        self._logprobs = {}
        return out

    def evict(self, rid: int):
        st = self._call(_w_evict, rid, epoch=self._epoch)
        self._apply_state(st)

    def reap_orphans(self) -> int:
        """Evict every sequence the worker is running (crash recovery:
        the worker outlived its frontend and those sequences are
        orphans); returns the count.  ``ServingFrontend.recover`` calls
        this on every still-live replica before re-admitting from the
        journal."""
        n, st = self._call(_w_reap_orphans, epoch=self._epoch)
        self._apply_state(st)
        self._finished.clear()
        self._logprobs.clear()
        return int(n)

    def export_blocks(self, hashes) -> Dict:
        """Pull a bit-exact KV payload off the worker (source side of a
        disaggregated block transfer, kv_fabric.py)."""
        return self._call(_w_export_blocks, list(hashes),
                          epoch=self._epoch)

    def import_blocks(self, payload: Dict) -> int:
        """Push a transferred KV payload into the worker's pool; the
        reply's state summary refreshes the mirror so prefix-affinity
        routing sees the imported hashes immediately."""
        n, st = self._call(_w_import_blocks, payload, epoch=self._epoch)
        self._apply_state(st)
        return int(n)

    def pull_blocks(self, peer_endpoint: str, hashes,
                    epoch: Optional[int] = None) -> Tuple[int, int]:
        """Make the worker pull a chain segment DIRECTLY off a peer's
        data-plane listener (``_w_pull_blocks``, ISSUE 20): the payload
        never touches this frontend — only this directory-sized control
        RPC does.  The worker's stamped epoch rides both the RPC and
        the wire handshake; the ``epoch`` parameter exists for engine-
        surface compatibility and is superseded by the stamp.  Returns
        ``(blocks_imported, payload_bytes)``."""
        n, nbytes, st = self._call(_w_pull_blocks, str(peer_endpoint),
                                   list(hashes),
                                   epoch=self._epoch if self._epoch
                                   is not None else epoch)
        self._apply_state(st)
        return int(n), int(nbytes)

    def load_weights(self, spec: Dict, version: Optional[str] = None,
                     model_id: Optional[str] = None) -> str:
        """Rolling-swap this worker to new version-labelled weights
        (ISSUE 18).  Duck-types ``ServingEngine.load_weights`` for the
        frontend's swap drivers, but takes the worker-spec RECIPE —
        ``{"seed": .., "model": {LlamaConfig kwargs}, "bfloat16": ..}``
        — not a model instance: the worker rebuilds the seeded weights
        itself (``_w_swap_weights``), so nothing tensor-sized crosses
        the wire and every replica of a version is bit-identical by
        construction.  Raises whatever the worker-side swap raised (an
        armed ``weights.swap`` failpoint, a geometry ValueError); the
        worker keeps its old version on any fault."""
        v, st = self._call(_w_swap_weights, dict(spec.get("model") or {}),
                           int(spec.get("seed", 0)), version, model_id,
                           bool(spec.get("bfloat16", False)),
                           epoch=self._epoch)
        self._apply_state(st)
        return v

    # --------------------------------------------------- fleet-layer extras
    def health(self, include_samples: bool = False,
               timeout: Optional[float] = None, retries: int = 0,
               retry_backoff_s: float = 0.05) -> Dict:
        """Probe the worker; ``timeout`` overrides the data-plane timeout
        (heartbeats use a short one so a hung worker is detected within
        ~a heartbeat interval, not after a full data-plane deadline).

        ``retries`` re-issues the probe after transient transport faults
        (RpcTimeout / connection errors) with exponential backoff — the
        probe is idempotent and read-only, so retrying is always safe,
        and one dropped packet must not fail over a healthy worker.  The
        data-plane ``step`` path deliberately has NO retry: it is not
        idempotent from the frontend's view (tokens could be emitted
        twice) and the existing failover re-queue already recovers it
        exactly."""
        last: Optional[BaseException] = None
        for attempt in range(int(retries) + 1):
            if attempt:
                time.sleep(retry_backoff_s * (2.0 ** (attempt - 1)))
            try:
                h = self._rpc.rpc_sync(self.worker, _w_health,
                                       args=(include_samples,),
                                       timeout=self.rpc_timeout
                                       if timeout is None else timeout)
                break
            except (TimeoutError, ConnectionError, OSError) as e:
                last = e       # transient transport shapes: retry
        else:
            raise last
        self._apply_state(h["state"])
        return h

    def request_shutdown(self, timeout: Optional[float] = None):
        self._rpc.rpc_sync(self.worker, _w_shutdown,
                           kwargs={"epoch": self._epoch},
                           timeout=self.rpc_timeout
                           if timeout is None else timeout)


@dataclass
class AutoscalePolicy:
    """Knobs for ``FleetAutoscaler`` (all observation-count based so tests
    can drive it deterministically with an injected clockless loop)."""

    min_workers: int = 1
    max_workers: int = 4
    # scale up when queued requests per accepting replica exceed this...
    scale_up_queue_per_replica: float = 2.0
    # ...or when p95 TTFT (from the frontend registry) exceeds this SLO
    scale_up_ttft_p95_s: Optional[float] = None
    # consecutive pressured/idle observations required to act
    up_after: int = 2
    down_after: int = 3
    # observations to wait after any scale action before the next one
    cooldown: int = 2


class FleetAutoscaler:
    """Queue-depth / SLO-pressure replica autoscaler.

    Call ``observe()`` once per control-plane iteration (ServingFleet does
    this from ``step()``).  Decisions: spawn a worker when sustained
    pressure (non-blocking — the boot happens off the step loop and the
    replica attaches when ready; booting workers count as capacity so
    pressure during the boot can't over-spawn), drain the most idle
    worker when sustained idleness, hold otherwise.  Drain = stop
    admitting (frontend ``draining`` flag), finish in-flight, deregister
    + reap (ServingFleet completes it once the replica is empty)."""

    def __init__(self, fleet: "ServingFleet",
                 policy: Optional[AutoscalePolicy] = None):
        self.fleet = fleet
        self.policy = policy or AutoscalePolicy()
        self._pressure = 0
        self._idle = 0
        self._cooldown = 0
        self.actions: List[str] = []  # audit trail ("up:worker2", ...)

    def observe(self) -> str:
        """One autoscaling observation; returns 'up', 'down', or 'hold'."""
        pol = self.policy
        fe = self.fleet.frontend
        if fe is None:  # fleet created with num_workers=0, none spawned yet
            return "hold"
        accepting = [r for r in fe.replicas if r.alive and not r.draining]
        if self._cooldown > 0:
            self._cooldown -= 1
            return "hold"
        queue_depth = len(fe._queue)
        per_rep = queue_depth / max(len(accepting), 1)
        pressured = per_rep > pol.scale_up_queue_per_replica
        if not pressured and pol.scale_up_ttft_p95_s is not None:
            # summary(), not snapshot(): this runs every fleet step and a
            # full snapshot sorts every latency buffer just to read one p95
            p95 = fe.metrics.summary("ttft_seconds")["p95"]
            pressured = p95 > pol.scale_up_ttft_p95_s
        busy = queue_depth > 0 or any(len(r.requests) for r in accepting)
        self._pressure = self._pressure + 1 if pressured else 0
        self._idle = self._idle + 1 if not busy else 0

        # workers already booting count as capacity on the way — without
        # this, every observation during the ~10 s boot would spawn one
        # more (the non-blocking spawn returns before the worker exists)
        pending = getattr(self.fleet, "num_pending_spawns", 0)
        if (self._pressure >= pol.up_after
                and len(accepting) + pending < pol.max_workers):
            # respawn circuit breaker: after K spawn-or-early-death
            # failures the fleet stops paying a doomed ~10 s boot per
            # observation; pressure is NOT reset, so the next allow()
            # (half-open probe after the jittered backoff) retries
            # immediately instead of re-accumulating up_after signals
            breaker = getattr(self.fleet, "spawn_breaker", None)
            if breaker is not None and not breaker.allow():
                if not self.actions or self.actions[-1] != "breaker:hold":
                    self.actions.append("breaker:hold")
                return "hold"
            spawn = getattr(self.fleet, "spawn_worker_async", None)
            name = spawn() if spawn is not None else self.fleet.spawn_worker()
            self.actions.append(f"up:{name}")
            self._pressure = 0
            self._cooldown = pol.cooldown
            return "up"
        if (self._idle >= pol.down_after
                and len(accepting) > pol.min_workers):
            victim = min(accepting, key=lambda r: len(r.requests))
            self.fleet.drain_replica(victim)
            self.actions.append(f"down:{victim.engine.worker}")
            self._idle = 0
            self._cooldown = pol.cooldown
            return "down"
        return "hold"


class WarmPool:
    """Pre-booted worker pool (ISSUE 18): scale-up as attach, not boot.

    A *warm* worker has already paid the ~10 s boot — jax import, seeded
    weight build, and step/megastep program compilation (driven by a
    throwaway sub-block request, so nothing lands in the prefix cache) —
    and parks registered-but-unattached behind a ``/serving/warm/<name>``
    KV marker.  ``FleetAutoscaler`` scale-up then claims one (a single
    health probe, ~ms) instead of spawning cold; the pool refills
    asynchronously behind the claim.

    The pool is deliberately host-mechanism-agnostic: ``spawn_fn(name)``
    launches one warm worker and either returns a ready handle
    immediately (synchronous fakes in tests) or returns ``None`` and
    arranges for ``note_ready(name, handle)`` / ``note_failed(name)``
    when the boot resolves (``ServingFleet`` does this on a daemon
    thread).  The spawn ``breaker`` is consulted before every refill —
    a crash-looping warm config backs off exactly like cold respawns —
    and both lifecycle edges fire chaos-drivable failpoints:
    ``pool.refill`` when a refill launches, ``pool.attach`` when a claim
    hands a worker out (a faulted claim re-pools the worker and the
    caller falls back to a cold spawn).

    Weight-swap coherence: the pool carries a ``generation``; a rolling
    weight swap drains the ready set and bumps it, so a warm worker that
    finished booting with pre-swap weights is refused by ``note_ready``
    and reaped by its owner instead of ever serving stale weights.

    Counters: ``pool_refills_total`` / ``pool_attaches_total`` /
    ``pool_attach_failures_total``; depth (ready + booting) is the
    ``warm_pool_depth`` gauge."""

    def __init__(self, size: int, spawn_fn: Callable[[str], Any], *,
                 breaker: Optional[RespawnCircuitBreaker] = None,
                 fault_injector: Optional[FaultInjector] = None,
                 metrics: Optional[ServingMetrics] = None,
                 name_prefix: str = "warm"):
        self.size = int(size)
        self.spawn_fn = spawn_fn
        self.breaker = breaker
        self.faults = fault_injector
        self.metrics = metrics
        self.name_prefix = name_prefix
        self.generation = 0
        self._lock = threading.Lock()
        self._ready: List = []                 # guarded-by: self._lock
        self._pending: Dict[str, int] = {}     # guarded-by: self._lock
        self._next = 0

    def _inc(self, name: str, n: int = 1):
        if self.metrics is not None:
            self.metrics.inc(name, n)

    def _sample_depth(self):
        if self.metrics is not None:
            self.metrics.set_gauge("warm_pool_depth", self.depth())

    def depth(self) -> int:
        """Ready + booting warm workers (the scale-up headroom gauge)."""
        with self._lock:
            return len(self._ready) + len(self._pending)

    def ready_names(self) -> List[str]:
        with self._lock:
            return [name for name, _ in self._ready]

    def refill(self) -> int:
        """Launch warm boots until depth reaches ``size``; returns how
        many were launched.  Consults the spawn breaker first (a pool
        must not crash-loop past containment just because it is a pool)
        and stops at the first spawn fault — the breaker holds the next
        attempt, and the periodic maintain retries after backoff."""
        launched = 0
        while self.depth() < self.size:
            if self.breaker is not None and not self.breaker.allow():
                break
            with self._lock:
                name = f"{self.name_prefix}{self._next}"
                self._next += 1
                self._pending[name] = self.generation
            try:
                if self.faults is not None:
                    self.faults.fire(POOL_REFILL, detail=name)
                handle = self.spawn_fn(name)
            # graft-lint: disable=typed-termination — refill containment:
            # any spawn fault (armed pool.refill, Popen failure) feeds the
            # breaker and the next maintain retries after its backoff
            except Exception:  # noqa: BLE001
                with self._lock:
                    self._pending.pop(name, None)
                if self.breaker is not None:
                    self.breaker.record_failure()
                self._inc("spawn_failures_total")
                self._sample_depth()
                break
            self._inc("pool_refills_total")
            launched += 1
            if handle is not None:     # synchronous spawn: ready now
                self.note_ready(name, handle)
        self._sample_depth()
        return launched

    def note_ready(self, name: str, handle: Any = None) -> bool:
        """A warm boot finished; pool it — unless the generation moved
        on (weights were swapped mid-boot), in which case the worker
        holds stale weights: refuse it (returns False) so the owner
        reaps it instead of ever attaching it."""
        with self._lock:
            gen = self._pending.pop(name, None)
            if gen is not None and gen != self.generation:
                self._sample_depth()
                return False
            self._ready.append((name, handle))
        self._sample_depth()
        return True

    def note_failed(self, name: str, record: bool = True):
        """A warm boot died; release its seat.  ``record=False`` when
        the caller's own spawn machinery already fed the breaker."""
        with self._lock:
            self._pending.pop(name, None)
        if record and self.breaker is not None:
            self.breaker.record_failure()
        self._sample_depth()

    def claim(self):
        """Pop the oldest ready warm worker as ``(name, handle)``, or
        ``None`` when the pool is empty (caller falls back to a cold
        spawn).  Fires ``pool.attach``; a faulted attach re-pools the
        worker (it is still warm and healthy — the fault was the attach
        edge) and returns ``None``."""
        with self._lock:
            if not self._ready:
                return None
            item = self._ready.pop(0)
        try:
            if self.faults is not None:
                self.faults.fire(POOL_ATTACH, detail=item[0])
        # graft-lint: disable=typed-termination — attach containment: the
        # worker goes back in the pool and the caller cold-spawns instead
        except Exception:  # noqa: BLE001
            self._inc("pool_attach_failures_total")
            with self._lock:
                self._ready.insert(0, item)
            return None
        self._inc("pool_attaches_total")
        self._sample_depth()
        return item

    def drain_ready(self, bump_generation: bool = True) -> List:
        """Remove and return every ready worker (rolling swap / shutdown
        — the caller owns reaping them).  Bumping the generation makes
        still-booting workers stale: their ``note_ready`` is refused."""
        with self._lock:
            ready, self._ready = self._ready, []
            if bump_generation:
                self.generation += 1
        self._sample_depth()
        return ready


class ServingFleet:
    """Remote-replica data plane: worker processes + frontend + heartbeat.

    >>> fleet = ServingFleet(worker_spec={"seed": 11, "model": {...},
    ...                                   "engine": {...}}, num_workers=2)
    >>> rid = fleet.frontend.submit([1, 5, 7], max_new_tokens=16)
    >>> results = fleet.run()
    >>> fleet.shutdown()

    ``worker_spec`` is the JSON-able model/engine recipe every spawned
    worker builds (seeded identically, so greedy decode is replica-
    independent).  Pass ``master_endpoint`` to join an existing KV master
    (e.g. workers pre-started on other hosts via ``attach_worker``);
    otherwise the fleet starts its own in-process ``KVServer``.
    ``cpu_workers=True`` (default) pins spawned workers to
    ``JAX_PLATFORMS=cpu`` exactly like the standalone-serving test
    subprocesses — pass False to let workers use the host's accelerator
    config."""

    def __init__(self, worker_spec: Dict, num_workers: int = 0, *,
                 master_endpoint: Optional[str] = None,
                 worker_roles: Optional[Sequence[Optional[str]]] = None,
                 frontend_kwargs: Optional[Dict] = None,
                 rpc_timeout: float = 60.0,
                 spawn_timeout: float = 120.0,
                 heartbeat_interval_s: float = 1.0,
                 heartbeat_timeout_s: float = 5.0,
                 heartbeat_retries: int = 1,
                 cpu_workers: bool = True,
                 autoscaler_policy: Optional[AutoscalePolicy] = None,
                 spawn_breaker: Optional[RespawnCircuitBreaker] = None,
                 early_death_s: float = 20.0,
                 max_spawn_errors: int = 32,
                 fault_injector: Optional[FaultInjector] = None,
                 warm_pool_size: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        from ..distributed import rpc
        from ..distributed.launch.master import KVClient, KVServer

        self.worker_spec = dict(worker_spec)
        # disaggregation: role label per launch index ('prefill'/'decode'/
        # None); workers past the list launch unlabeled.  The label is
        # injected into each worker's spec JSON, so it rides the same
        # wire the engine config does and survives respawns by name.
        self.worker_roles = (list(worker_roles)
                             if worker_roles is not None else [])
        self.rpc_timeout = float(rpc_timeout)
        self.spawn_timeout = float(spawn_timeout)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        # idempotent health probes survive one transient transport fault
        # by default; data-plane step RPCs stay fail-fast into failover
        self.heartbeat_retries = int(heartbeat_retries)
        self.cpu_workers = bool(cpu_workers)
        self._clock = clock
        self._rpc = rpc
        # respawn containment: spawn failures and early worker deaths feed
        # this breaker; the autoscaler consults it before every spawn, so
        # a crash-looping worker config backs off exponentially instead of
        # burning a ~10 s boot per observation forever.  Async boot
        # threads race record_failure against the control thread's
        # allow/record_success/open_gauge — the breaker locks its own
        # state machine, so no caller-side locking is needed here
        self.spawn_breaker = (spawn_breaker if spawn_breaker is not None
                              else RespawnCircuitBreaker(clock=clock))
        self.early_death_s = float(early_death_s)
        self._attached_at: Dict[str, float] = {}
        self._faults = (fault_injector if fault_injector is not None
                        else FaultInjector.from_env())
        self._max_spawn_errors = int(max_spawn_errors)
        self._kv_server = None
        if master_endpoint is None:
            self._kv_server = KVServer(0).start()
            master_endpoint = f"127.0.0.1:{self._kv_server.port}"
        self.master_endpoint = master_endpoint
        self._kv = KVClient(master_endpoint)
        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs: Dict[str, str] = {}
        self._next_worker = 0
        self._last_heartbeat = -float("inf")
        # non-blocking scale-up state: background threads wait out worker
        # boot (jax import + first-step compile, ~10 s) and park the ready
        # RemoteReplica here; step() attaches it on the control thread so
        # frontend structures are never mutated concurrently
        self._spawn_lock = threading.Lock()
        self._pending_spawns: Dict[str, threading.Thread] = {}  # guarded-by: self._spawn_lock
        self._ready_replicas: List = []                         # guarded-by: self._spawn_lock
        # guarded-by: self._spawn_lock
        self.spawn_errors: Dict[str, str] = _BoundedErrors(
            self._max_spawn_errors)
        self._frontend_kwargs = dict(frontend_kwargs or {})
        self.frontend: Optional[ServingFrontend] = None
        self.autoscaler: Optional[FleetAutoscaler] = None
        self.warm_pool: Optional[WarmPool] = None
        self._rpc_inited = False
        # from here on every failure funnels through shutdown() so the
        # just-started KVServer (thread + port) cannot leak — init_rpc
        # itself raises when this process already has an rpc session
        try:
            rpc.init_rpc("fleet-frontend", rank=0, world_size=1,
                         master_endpoint=master_endpoint)
            self._rpc_inited = True
            names = [self._launch() for _ in range(num_workers)]
            for name in names:
                self._await_worker(name)
        except Exception:
            self.shutdown()
            raise
        if autoscaler_policy is not None:
            self.autoscaler = FleetAutoscaler(self, autoscaler_policy)
        if warm_pool_size > 0:
            # warm-worker pool (ISSUE 18): start the first refill now so
            # the boots overlap initial serving; step() keeps it topped up
            self.warm_pool = WarmPool(warm_pool_size, self._spawn_warm,
                                      breaker=self.spawn_breaker,
                                      fault_injector=self._faults)
            self.warm_pool.refill()

    # ------------------------------------------------------- worker launch
    def _worker_script(self) -> str:
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        return os.path.join(here, "tools", "serving_worker.py")

    def _launch(self, name: Optional[str] = None,
                role: Optional[str] = None, warm: bool = False) -> str:
        """Start a worker process (non-blocking); pair with _await_worker.
        ``warm=True`` boots a pool worker: it pre-compiles its programs
        BEFORE registering and parks behind a ``/serving/warm/`` marker
        (claimed by ``WarmPool``, invisible to discovery until then)."""
        if name is None:
            idx = self._next_worker
            name = f"worker{idx}"
            self._next_worker += 1
            if role is None and idx < len(self.worker_roles):
                role = self.worker_roles[idx]
        spec = dict(self.worker_spec)
        if role is not None:
            spec["role"] = role
        cmd = [sys.executable, self._worker_script(),
               "--master", self.master_endpoint, "--name", name,
               "--spec-json", json.dumps(spec)]
        if warm:
            cmd += ["--warm"]
        if self.cpu_workers:
            cmd += ["--platform", "cpu"]
        # stderr to a file, not a pipe: nobody drains worker pipes and a
        # chatty worker (jax warnings) would block on a full pipe buffer
        log = tempfile.NamedTemporaryFile(
            mode="w+", prefix=f"paddle_tpu_{name}_", suffix=".log",
            delete=False)
        self._logs[name] = log.name
        self._procs[name] = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT)
        log.close()
        return name

    def worker_log(self, name: str, tail: int = 2000) -> str:
        path = self._logs.get(name)
        if not path or not os.path.exists(path):
            return ""
        with open(path) as f:
            return f.read()[-tail:]

    def _await_registration(self, name: str):
        """Block until ``name`` registers with the KV master (raising, and
        reaping the process, on early exit or timeout)."""
        proc = self._procs[name]
        if self._faults is not None:
            try:
                self._faults.fire("fleet.spawn", detail=name)
            except Exception:
                # the injected spawn fault must leave no zombie behind —
                # same reap discipline as the real early-exit path below
                proc.kill()
                proc.wait(timeout=10)
                self._procs.pop(name, None)
                self._drop_log(name)
                raise
        # real wall clock, NOT the injectable self._clock: this loop
        # actually sleeps, and a frozen/jumping test clock would make the
        # spawn deadline never (or spuriously) fire
        # graft-lint: disable=determinism — see above: boot deadline on a
        # real subprocess, never replayed
        deadline = time.monotonic() + self.spawn_timeout
        while self._kv.get(f"/rpc/workers/{name}") is None:
            if proc.poll() is not None:
                err = self.worker_log(name)
                self._procs.pop(name, None)
                self._drop_log(name)
                raise RuntimeError(
                    f"serving worker '{name}' exited rc={proc.returncode} "
                    f"before registering:\n{err}")
            # graft-lint: disable=determinism — same real boot deadline
            if time.monotonic() > deadline:
                proc.kill()
                proc.wait(timeout=10)  # reap — no zombie behind the raise
                self._procs.pop(name, None)
                self._drop_log(name)
                raise TimeoutError(
                    f"serving worker '{name}' did not register within "
                    f"{self.spawn_timeout}s")
            time.sleep(0.05)

    def _await_worker(self, name: str):
        """Block until ``name`` registers with the KV master, then attach
        its RemoteReplica to the frontend."""
        self._await_registration(name)
        self._rpc.refresh_workers()
        self.attach_worker(name)

    def _make_replica(self, name: str):
        """RemoteReplica factory (constructing one IS the readiness probe:
        its ``__init__`` round-trips the worker's health RPC).  Split out
        so tests can stand in a fake replica without subprocess boots."""
        return RemoteReplica(name, rpc_timeout=self.rpc_timeout)

    def _inc_metric(self, name: str, n: int = 1):
        """Fleet-layer counter increments land in the frontend registry
        (the one the Prometheus fleet page exports under the 'frontend'
        replica label); dropped silently before the first worker attaches
        — there is no registry to count into yet."""
        if self.frontend is not None:
            self.frontend.metrics.inc(name, n)

    def _note_spawn_failure(self, name: str, err: str):
        """Shared bookkeeping for every spawn-path fault (blocking spawn,
        async boot thread, early worker death): bounded error ring,
        breaker failure, counter.  Runs on the control thread (blocking
        ``spawn_worker``) AND on async boot threads (``_spawn_wait``)
        [lock-discipline]: the error ring takes the spawn lock (callers
        must NOT already hold it); the breaker locks itself, and its
        record_failure returns the open transition atomically so two
        racing reporters cannot double-count ``breaker_open_total``."""
        with self._spawn_lock:
            self.spawn_errors[name] = err
        if self.spawn_breaker.record_failure():
            self._inc_metric("breaker_open_total")
        self._inc_metric("spawn_failures_total")

    def _attach_replica(self, replica):
        # NOT a breaker success yet: a crash-looping config usually boots
        # and attaches fine, then dies on first real work — success is
        # recorded only when the replica SURVIVES early_death_s (the
        # maturation sweep in step()), so attach/die cycles accumulate
        # failures instead of resetting the window every boot
        name = getattr(replica, "worker", None)
        if name is not None:
            self._attached_at[name] = self._clock()
        if self.frontend is None:
            self.frontend = ServingFrontend([replica],
                                            **self._frontend_kwargs)
        else:
            self.frontend.add_replica(replica)
        return replica

    def attach_worker(self, name: str):
        """Wrap an already-registered worker (spawned here or started by an
        operator on another host) in a RemoteReplica and route to it."""
        self._rpc.refresh_workers()
        return self._attach_replica(self._make_replica(name))

    def spawn_worker(self, name: Optional[str] = None,
                     role: Optional[str] = None) -> str:
        """Launch + register + attach one new worker.  Blocking: the
        worker is routable when this returns (initial fleet bring-up; the
        autoscaler's in-loop scale-up uses ``spawn_worker_async``)."""
        # only forward role= when asked: tests monkeypatch _launch with
        # role-unaware fakes, and the default path must keep working
        name = (self._launch(name, role=role) if role is not None
                else self._launch(name))
        try:
            self._await_worker(name)
        except Exception as e:  # noqa: BLE001 — feed the respawn breaker
            self._note_spawn_failure(name, repr(e))
            raise
        return name

    def spawn_worker_async(self, name: Optional[str] = None) -> str:
        """Non-blocking scale-up: launch the worker process and return its
        name immediately.  A daemon thread waits out KV registration and
        the first health probe (the ~10 s jax-import + compile boot that
        used to stall the step loop), then parks the ready RemoteReplica;
        the next ``step()`` attaches it on the control thread.  Spawn
        failures are recorded in ``spawn_errors`` (the autoscaler's
        pending count drops either way, so it can try again).

        With a warm pool armed (ISSUE 18), a ready warm worker is claimed
        INSTEAD of launching cold: the worker already booted and compiled,
        so "spawn" collapses to one health probe and the replica attaches
        on the next step — near-zero time-to-capacity.  The pool refills
        asynchronously behind the claim; an empty pool (or a faulted
        ``pool.attach``) falls through to the cold path unchanged."""
        if name is None and self.warm_pool is not None:
            if self.warm_pool.metrics is None and self.frontend is not None:
                # a claim can precede the first control-loop step — bind
                # the pool's counters now so the attach is not invisible
                self.warm_pool.metrics = self.frontend.metrics
            claimed = self.warm_pool.claim()
            if claimed is not None:
                wname = claimed[0]
                # claimed: drop the warm marker so discovery treats it as
                # a normal worker from here on (recovery must see it)
                self._kv.delete(f"/serving/warm/{wname}")
                t = threading.Thread(target=self._adopt_warm, args=(wname,),
                                     name=f"fleet-adopt-{wname}", daemon=True)
                with self._spawn_lock:
                    self._pending_spawns[wname] = t
                t.start()
                self.warm_pool.refill()
                return wname
        name = self._launch(name)
        t = threading.Thread(target=self._spawn_wait, args=(name,),
                             name=f"fleet-spawn-{name}", daemon=True)
        with self._spawn_lock:
            self._pending_spawns[name] = t
        t.start()
        return name

    def _spawn_wait(self, name: str):
        try:
            self._await_registration(name)
            self._rpc.refresh_workers()
            replica = self._make_replica(name)
        except Exception as e:  # noqa: BLE001 — boot fault, record + reap
            # failure first, seat second: the autoscaler must never
            # observe the seat free without the failure recorded (it
            # would spawn a doomed extra worker past max_workers)
            self._note_spawn_failure(name, repr(e))  # takes _spawn_lock
            with self._spawn_lock:
                self._pending_spawns.pop(name, None)
            proc = self._procs.pop(name, None)
            if proc is not None:
                try:
                    if proc.poll() is None:
                        proc.kill()
                    proc.wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired):
                    pass   # reaped at shutdown() if truly unkillable
                self._drop_log(name)
            return
        with self._spawn_lock:
            # the _pending_spawns seat is NOT released here: it must hold
            # until the replica is actually attached, or the autoscaler
            # could observe in the ready-but-unattached window and spawn
            # past max_workers
            self._ready_replicas.append((name, replica))

    # ----------------------------------------------------- warm pool hooks
    def _spawn_warm(self, name: str):
        """``WarmPool`` spawn hook: launch a ``--warm`` worker and wait
        out its (pre-compiling) boot on a daemon thread; the pool's
        pending seat holds until ``note_ready``/``note_failed``.
        Returns None — the async contract of ``WarmPool.spawn_fn``."""
        self._launch(name, warm=True)
        t = threading.Thread(target=self._warm_wait, args=(name,),
                             name=f"fleet-warm-{name}", daemon=True)
        t.start()
        return None

    def _warm_wait(self, name: str):
        try:
            self._await_registration(name)
        except Exception as e:  # noqa: BLE001 — warm boot fault: record
            # + release the pool seat (registration already reaped the
            # process); record=False — _note_spawn_failure feeds the
            # breaker, the pool must not count the same death twice
            self._note_spawn_failure(name, repr(e))
            if self.warm_pool is not None:
                self.warm_pool.note_failed(name, record=False)
            return
        if self.warm_pool is None or not self.warm_pool.note_ready(name):
            # pool generation moved on while this worker booted (weights
            # were swapped / shutdown): it holds stale state — reap it
            # rather than ever pooling or attaching it
            self._reap_proc(name, kill=True)

    def _adopt_warm(self, name: str):
        """Attach side of a warm claim: the worker already booted and
        compiled, so all that remains is one health probe (the
        RemoteReplica constructor) — the near-zero-latency attach the
        pool exists for.  Runs on a daemon thread like ``_spawn_wait``;
        the next ``step()`` attaches the parked replica."""
        try:
            self._rpc.refresh_workers()
            replica = self._make_replica(name)
        except Exception as e:  # noqa: BLE001 — probe fault on a claimed
            # warm worker: same containment as a failed cold boot
            self._note_spawn_failure(name, repr(e))
            self._inc_metric("pool_attach_failures_total")
            with self._spawn_lock:
                self._pending_spawns.pop(name, None)
            self._reap_proc(name, kill=True)
            return
        with self._spawn_lock:
            self._ready_replicas.append((name, replica))

    def _flush_warm_pool(self):
        """Reap every READY warm worker and refill (rolling swap: pooled
        workers hold pre-swap weights and must never attach; the
        generation bump makes still-booting ones refuse pooling too)."""
        if self.warm_pool is None:
            return
        for wname, _ in self.warm_pool.drain_ready():
            self._kv.delete(f"/serving/warm/{wname}")
            self._reap_proc(wname, kill=True)
        self.warm_pool.refill()

    @property
    def num_pending_spawns(self) -> int:
        """Workers launched asynchronously but not yet attached — the
        autoscaler counts these as capacity already on the way."""
        with self._spawn_lock:
            return len(self._pending_spawns)

    def _attach_ready(self):
        """Attach replicas whose async spawn completed (control thread
        only — frontend structures are single-threaded); the pending
        seat is released only now, with the replica live."""
        with self._spawn_lock:
            ready, self._ready_replicas = self._ready_replicas, []
            for name, _ in ready:
                self._pending_spawns.pop(name, None)
        for _, replica in ready:
            self._attach_replica(replica)

    def _note_matured_replicas(self):
        """Replicas alive past ``early_death_s`` since attach count as
        spawn SUCCESSES: this is what re-closes a half-open breaker (the
        probe worker proved itself) and clears the failure window after
        genuine recovery.  Recording at attach instead would let a
        boots-fine-dies-early crash loop reset the window every cycle
        and the breaker would never open."""
        if self.frontend is None:
            return
        now = self._clock()
        for rep in self.frontend.replicas:
            if not rep.alive:
                continue
            name = getattr(rep.engine, "worker", None)
            att = self._attached_at.get(name) if name is not None else None
            if att is not None and now - att >= self.early_death_s:
                self._attached_at.pop(name, None)
                self.spawn_breaker.record_success()

    # ------------------------------------------------------------- driving
    @property
    def workers(self) -> List[str]:
        if self.frontend is None:
            return []
        return [r.engine.worker for r in self.frontend.replicas
                if isinstance(r.engine, RemoteReplica)]

    def _require_frontend(self) -> ServingFrontend:
        if self.frontend is None:
            raise RuntimeError(
                "ServingFleet has no workers yet (num_workers=0 and nothing "
                "attached) — spawn_worker()/attach_worker() first")
        return self.frontend

    def step(self):
        """One fleet iteration: attach async-spawned replicas, heartbeat
        (rate-limited), autoscale (if attached), frontend step, reap
        drained/dead workers."""
        self._attach_ready()
        fe = self._require_frontend()
        self._note_matured_replicas()
        now = self._clock()
        if now - self._last_heartbeat >= self.heartbeat_interval_s:
            self._last_heartbeat = now
            self.heartbeat()
        if self.autoscaler is not None:
            self.autoscaler.observe()
        fe.metrics.set_gauge("respawn_breaker_open",
                             self.spawn_breaker.open_gauge)
        if self.warm_pool is not None:
            # bind the pool's counters to the frontend registry (it may
            # not have existed at pool creation) and keep it topped up —
            # refill is a no-op depth check when the pool is full
            if self.warm_pool.metrics is None:
                self.warm_pool.metrics = fe.metrics
            self.warm_pool.refill()
            fe.metrics.set_gauge("warm_pool_depth", self.warm_pool.depth())
        fe.step()
        self._reap()

    def run(self, max_steps: int = 10_000):
        """Drive ``step()`` until every submitted request has a result
        (same contract/failure mode as ``ServingFrontend.run``)."""
        fe = self._require_frontend()
        for _ in range(max_steps):
            if not fe.pending:
                break
            self.step()
        if fe.pending:
            raise RuntimeError(
                f"ServingFleet.run: max_steps={max_steps} exhausted with "
                f"{fe.pending} unresolved request(s)")
        return fe.results()

    def heartbeat(self):
        """Probe every live replica's health RPC; a silent worker (probe
        raises — SIGKILLed process, or a hung handler past the SHORT
        ``heartbeat_timeout_s``, so detection is bounded by roughly one
        interval rather than the 60 s data-plane deadline) is failed over
        exactly like a step() fault: marked dead, in-flight requests
        re-queued from frontend-side state."""
        if self.frontend is None:
            return
        for rep in self.frontend.replicas:
            if not rep.alive or not isinstance(rep.engine, RemoteReplica):
                continue
            try:
                if self._faults is not None:
                    self._faults.fire("fleet.heartbeat",
                                      detail=rep.engine.worker)
                # transient-fault retry: the probe is idempotent, so one
                # dropped/slow packet re-probes instead of failing over a
                # healthy worker (a genuinely dead one fails every retry
                # and still dies within this heartbeat)
                rep.engine.health(timeout=self.heartbeat_timeout_s,
                                  retries=self.heartbeat_retries)
            except Exception as e:  # noqa: BLE001 — any probe fault = dead
                self.frontend.fail_replica(rep, e)

    # ------------------------------------------------------------- swapping
    def rolling_swap(self, spec: Dict, version: str, *,
                     model_id: Optional[str] = None,
                     max_steps: int = 10_000) -> int:
        """Fleet-wide zero-downtime weight swap (ISSUE 18): one replica
        at a time, drain → ``_w_swap_weights`` (the worker rebuilds the
        seeded weights from ``spec`` — the worker-spec recipe, nothing
        tensor-sized on the wire) → re-admit.  Drives ``self.step`` while
        draining so heartbeats, autoscaling, and warm-pool maintenance
        keep running.  On success the fleet's own ``worker_spec`` is
        updated too, so respawned workers and future warm boots come up
        on the NEW version instead of silently rolling back; the warm
        pool's pre-swap workers are reaped and the pool refilled.
        Returns the number of replicas now serving ``version``."""
        fe = self._require_frontend()
        n = fe.rolling_swap(spec, version, model_id=model_id,
                            step=self.step, max_steps=max_steps)
        if n:
            for key in ("seed", "model", "bfloat16"):
                if key in spec:
                    self.worker_spec[key] = spec[key]
            # respawns must come up LABELLED as the new version, not v0
            self.worker_spec["weights_version"] = version
            if model_id is not None:
                self.worker_spec["model_id"] = model_id
            self._flush_warm_pool()
        return n

    # ------------------------------------------------------------ draining
    def drain_replica(self, rep):
        """Begin scale-down of one replica: stop admitting to it; once its
        in-flight work finishes, ``step()`` deregisters the worker and
        reaps the process."""
        rep.draining = True

    def _reap(self):
        for rep in list(self.frontend.replicas):
            if not isinstance(rep.engine, RemoteReplica):
                continue
            name = rep.engine.worker
            if getattr(rep, "swapping", False):
                # drained-for-swap, not scale-down (ISSUE 18): the swap
                # driver re-admits this replica — reaping it here would
                # turn every rolling swap into a worker funeral
                continue
            if rep.alive and rep.draining and not rep.requests \
                    and not rep.engine._queue and not rep.engine._active:
                try:
                    # a drained worker is idle; the short probe timeout is
                    # the right bound (a wedged one just gets SIGKILLed)
                    rep.engine.request_shutdown(self.heartbeat_timeout_s)
                # graft-lint: disable=typed-termination — best-effort
                # polite stop; _reap_proc below SIGTERM/SIGKILLs anyway
                except Exception:  # noqa: BLE001
                    pass
                self._attached_at.pop(name, None)   # drained, not dead
                self.frontend.remove_replica(rep)
                self._reap_proc(name)
            elif not rep.alive:
                # failover already re-queued its requests; deregister
                att = self._attached_at.pop(name, None)
                if (att is not None
                        and self._clock() - att < self.early_death_s):
                    # spawn-or-early-death: a worker that dies this soon
                    # after attaching counts against the respawn breaker
                    # exactly like a failed spawn — a crash-looping config
                    # usually boots fine and dies on first real work
                    self._note_spawn_failure(
                        name, f"early death: replica died within "
                        f"{self.early_death_s}s of attach "
                        f"({rep.last_error})")
                self.frontend.remove_replica(rep)
                self._reap_proc(name, kill=True)

    def _reap_proc(self, name: str, kill: bool = False, timeout: float = 30):
        # the KV deregistration must happen even for externally-attached
        # workers (no local Popen): a stale /rpc/workers entry would keep
        # a dead worker in everyone's routing table on the next refresh
        self._kv.delete(f"/rpc/workers/{name}")
        self._kv.delete(f"/serving/roles/{name}")  # role label rides along
        self._kv.delete(f"/serving/wire/{name}")   # data-plane endpoint too
        proc = self._procs.pop(name, None)
        if proc is None:
            return
        try:
            if kill and proc.poll() is None:
                proc.kill()
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
        self._drop_log(name)

    def _drop_log(self, name: str):
        path = self._logs.pop(name, None)
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass

    # ------------------------------------------------------------- metrics
    def worker_snapshots(self, include_samples: bool = True) -> Dict[str, Dict]:
        """{worker_name: metrics snapshot} from every reachable replica."""
        out: Dict[str, Dict] = {}
        for rep in self.frontend.replicas:
            if not rep.alive or not isinstance(rep.engine, RemoteReplica):
                continue
            try:
                out[rep.engine.worker] = \
                    rep.engine.health(include_samples)["metrics"]
            # graft-lint: disable=typed-termination — scrape path: a
            # worker that cannot answer is simply absent from this page;
            # the heartbeat (not the scraper) owns declaring it dead
            except Exception:  # noqa: BLE001
                pass
        return out

    def reset_worker_metrics(self):
        """Zero every reachable worker's registry (pair with
        ``frontend.metrics.reset()`` when excluding a warmup window)."""
        for rep in self.frontend.replicas:
            if not rep.alive or not isinstance(rep.engine, RemoteReplica):
                continue
            try:
                self._rpc.rpc_sync(rep.engine.worker, _w_reset_metrics,
                                   kwargs={"epoch": rep.engine._epoch},
                                   timeout=rep.engine.rpc_timeout)
            # graft-lint: disable=typed-termination — warmup-window reset
            # is advisory; an unreachable worker keeps its counters and
            # the heartbeat owns its fate
            except Exception:  # noqa: BLE001
                pass

    def merged_snapshot(self) -> Dict:
        """One fleet-wide engine-level snapshot (ServingMetrics.merge of
        the per-worker registries).  Request-level metrics (TTFT, e2e,
        admission counters) live in ``self.frontend.metrics`` — the two
        views count different things, so they are not summed together."""
        return ServingMetrics.merge(self.worker_snapshots())

    def prometheus_text(self) -> str:
        """One scrape page: every worker's engine-level series plus the
        frontend's request-level series, each with a ``replica`` label.
        Rendering only reads the precomputed quantile summaries, so the
        raw sample buffers (up to ~1.5 MB pickled per worker) stay out of
        the per-scrape RPCs — ``merged_snapshot`` is the path that needs
        them for exact fleet-wide percentiles."""
        snaps = dict(self.worker_snapshots(include_samples=False))
        snaps["frontend"] = self.frontend.metrics.snapshot()
        return ServingMetrics.prometheus_text_fleet(snaps)

    # ------------------------------------------------------------ shutdown
    def shutdown(self):
        """Stop every worker (polite RPC first, then kill), the RPC state,
        and the KV master.  Idempotent."""
        if self.warm_pool is not None:
            # stop refills first, then drop the warm markers (best
            # effort: the KV master may already be gone); the pooled
            # processes are in self._procs and die with everyone below
            self.warm_pool.size = 0
            for wname, _ in self.warm_pool.drain_ready():
                try:
                    self._kv.delete(f"/serving/warm/{wname}")
                # graft-lint: disable=typed-termination — best-effort
                # marker cleanup during teardown
                except Exception:  # noqa: BLE001
                    pass
        if self.frontend is not None:
            for rep in self.frontend.replicas:
                if rep.alive and isinstance(rep.engine, RemoteReplica):
                    try:
                        # heartbeat timeout, not the 60 s data-plane one: a
                        # hung worker must not stall shutdown per replica
                        rep.engine.request_shutdown(self.heartbeat_timeout_s)
                    # graft-lint: disable=typed-termination — best-effort
                    # polite stop during shutdown; SIGTERM/SIGKILL follow
                    except Exception:  # noqa: BLE001
                        pass
        for name, proc in list(self._procs.items()):
            # SIGTERM (the worker installs a handler that sets its stop
            # event) covers workers that never got the polite RPC — e.g.
            # a spawn that timed out mid-__init__ — without the 15 s stall
            if proc.poll() is None:
                proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            self._procs.pop(name, None)
            self._drop_log(name)
        if self._rpc_inited:
            # only tear down the rpc session THIS fleet created — when
            # init_rpc refused because the process already had one (e.g. a
            # concurrent fleet), that session belongs to someone else
            self._rpc.shutdown()
            self._rpc_inited = False
        if self._kv_server is not None:
            self._kv_server.stop()
            self._kv_server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
