"""Inference engine (parity:
/root/reference/paddle/fluid/inference/api/analysis_predictor.h:105
AnalysisPredictor + paddle_inference_api.h Config/create_predictor surface).

TPU-native: the "analysis + IR passes + engine selection" stack collapses to
XLA — a Predictor AOT-compiles the forward with ``jax.jit`` (or executes a
``.jaxexport`` artifact saved by ``jit.save``), caches one executable per
input-shape bucket, and optionally rewrites Linear layers to weight-only
int8 (int8 HBM storage, bf16 MXU compute) before compilation.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor

__all__ = ["Config", "create_predictor", "Predictor", "PredictorPool",
           "BlockManager", "ServingEngine", "ServingRequest",
           "SamplingParams", "ServingFrontend", "ServingMetrics",
           "Priority", "RequestStatus", "RequestResult", "ServingFleet",
           "RemoteReplica", "FleetAutoscaler", "AutoscalePolicy",
           "BrownoutPolicy", "FaultInjector", "FaultSpec",
           "RespawnCircuitBreaker", "RequestJournal", "JournalCorruption",
           "JournalSuperseded", "StaleEpoch", "EpochFence", "FencedEngine",
           "FrontendLease", "StandbyFrontend", "HandedOff",
           "TraceContext", "FlightRecorder", "Tracer",
           "TenantRegistry", "TenantSpec", "WarmPool"]

from .control_plane import (  # noqa: E402
    BrownoutPolicy,
    HandedOff,
    Priority,
    RequestResult,
    RequestStatus,
    ServingFrontend,
)
from .faults import (  # noqa: E402
    FaultInjector,
    FaultSpec,
    RespawnCircuitBreaker,
)
from .fleet import (  # noqa: E402
    AutoscalePolicy,
    FleetAutoscaler,
    RemoteReplica,
    ServingFleet,
    WarmPool,
)
from .ha import (  # noqa: E402
    EpochFence,
    FencedEngine,
    FrontendLease,
    StaleEpoch,
    StandbyFrontend,
)
from .journal import (  # noqa: E402
    JournalCorruption,
    JournalSuperseded,
    RequestJournal,
)
from .metrics import ServingMetrics  # noqa: E402
from .tenancy import (  # noqa: E402
    TenantRegistry,
    TenantSpec,
)
from .serving import (  # noqa: E402
    BlockManager,
    SamplingParams,
    ServingEngine,
    ServingRequest,
)
from .tracing import (  # noqa: E402
    FlightRecorder,
    TraceContext,
    Tracer,
)


class Config:
    """parity: paddle.inference.Config."""

    def __init__(self, model_path: Optional[str] = None, params_path: Optional[str] = None):
        # model_path is the jit.save path prefix (params_path kept for API parity)
        self.model_path = model_path
        self.params_path = params_path
        self._weight_only = None
        self._memory_optim = True
        self._ir_optim = True
        self._layer = None
        self._batch_pad = False

    # --- capability toggles (XLA owns these; kept for API parity) ---
    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def set_cpu_math_library_num_threads(self, n):
        pass

    def disable_glog_info(self):
        pass

    def enable_use_gpu(self, *a, **k):
        pass  # device residency is PJRT's concern

    def enable_xpu(self, *a, **k):
        pass

    # --- real knobs ---
    def enable_weight_only_quant(self, dtype="int8"):
        if dtype != "int8":
            raise NotImplementedError("weight-only quant supports int8")
        self._weight_only = dtype

    def enable_batch_padding(self, flag=True):
        """Pad smaller batches up to the compiled batch instead of recompiling."""
        self._batch_pad = flag

    def set_layer(self, layer):
        """Serve a live Layer (instead of a saved artifact)."""
        self._layer = layer


class _Handle:
    """Input/output tensor handle (ZeroCopyTensor analog)."""

    def __init__(self, name):
        self.name = name
        self._val = None

    def copy_from_cpu(self, arr):
        self._val = jnp.asarray(arr)

    def reshape(self, shape):
        pass  # shape comes from the array itself

    def copy_to_cpu(self):
        return np.asarray(self._val)

    def share_external_data(self, arr):
        self.copy_from_cpu(arr)


class Predictor:
    def __init__(self, config: Config):
        self.config = config
        self._cache: Dict[tuple, object] = {}
        self._loaded = None
        self._layer = config._layer
        if config.model_path and self._layer is None:
            from ..jit.serialization import load as jit_load

            self._loaded = jit_load(config.model_path)
            if config._weight_only is not None:
                import warnings

                warnings.warn(
                    "enable_weight_only_quant has no effect on a saved artifact "
                    "(weights are baked into the compiled program); build the "
                    "predictor from a live Layer via config.set_layer() to "
                    "serve int8 weights")
        if self._layer is not None and config._weight_only == "int8":
            self._layer = _rewrite_weight_only_int8(self._layer)
        self._inputs: Dict[str, _Handle] = {}
        self._outputs: List[np.ndarray] = []
        self._input_names: List[str] = []
        if self._loaded is not None and self._loaded.meta.get("input_spec"):
            self._input_names = [f"x{i}" for i in range(len(self._loaded.meta["input_spec"]))]

    # ----------------------------------------------------------- handles API
    def get_input_names(self):
        return self._input_names or sorted(self._inputs)

    def get_input_handle(self, name):
        h = self._inputs.get(name)
        if h is None:
            h = self._inputs[name] = _Handle(name)
            if name not in self._input_names:
                self._input_names.append(name)
        return h

    def get_output_names(self):
        return [f"out{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name):
        i = int(name.replace("out", ""))
        h = _Handle(name)
        h._val = self._outputs[i]
        return h

    # ----------------------------------------------------------------- run
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is None:
            inputs = [self._inputs[n]._val for n in self._input_names]
        vals = [jnp.asarray(v) for v in inputs]

        if self._loaded is not None:
            spec = self._loaded.meta.get("input_spec") or []
            if self.config._batch_pad and spec:
                vals, real_n = _pad_batch(vals, spec)
                outs = self._loaded(*[Tensor(v) for v in vals])
                outs = outs if isinstance(outs, list) else [outs]
                self._outputs = [np.asarray(o._value)[:real_n] for o in outs]
            else:
                outs = self._loaded(*[Tensor(v) for v in vals])
                outs = outs if isinstance(outs, list) else [outs]
                self._outputs = [np.asarray(o._value) for o in outs]
            return self._outputs

        key = tuple((v.shape, str(v.dtype)) for v in vals)
        compiled = self._cache.get(key)
        if compiled is None:
            layer = self._layer
            layer.eval()
            from ..autograd import tape
            from ..jit.api import flatten_tensors

            def fwd(*xs):
                with tape.no_grad():
                    out = layer(*[Tensor(x) for x in xs])
                outs, _ = flatten_tensors(out)
                return tuple(t._value for t in outs)

            compiled = jax.jit(fwd)
            self._cache[key] = compiled
        outs = compiled(*vals)
        self._outputs = [np.asarray(o) for o in outs]
        return self._outputs


def _pad_batch(vals, spec):
    """Pad dim-0 of each input up to the exported batch; return real size."""
    real_n = int(vals[0].shape[0])
    out = []
    for v, sm in zip(vals, spec):
        want = sm["shape"][0] or 1
        if v.shape[0] < want:
            pad = [(0, want - v.shape[0])] + [(0, 0)] * (v.ndim - 1)
            v = jnp.pad(v, pad)
        elif v.shape[0] > want:
            raise ValueError(f"batch {v.shape[0]} exceeds compiled batch {want}")
        out.append(v)
    return out, real_n


def _rewrite_weight_only_int8(layer):
    """Swap Linear sublayers for int8-storage equivalents."""
    import copy as _copy

    from ..nn import Linear
    from ..nn.layer.layers import Layer as _Layer
    from ..quantization import weight_only_linear, weight_quantize

    layer = _copy.deepcopy(layer)

    class Int8Linear(_Layer):
        def __init__(self, lin):
            super().__init__()
            self.qweight, self.scale = weight_quantize(lin.weight)
            self.bias = lin.bias

        def forward(self, x):
            return weight_only_linear(x, self.qweight, self.bias, self.scale)

    def rewrite(parent):
        for name, sub in list(parent._sub_layers.items()):
            if isinstance(sub, Linear):
                parent._sub_layers[name] = Int8Linear(sub)
            else:
                rewrite(sub)

    rewrite(layer)
    return layer


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class PredictorPool:
    """parity: paddle_infer.PredictorPool — N predictors over one config."""

    def __init__(self, config: Config, size: int = 1):
        self._preds = [Predictor(config) for _ in range(size)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]
