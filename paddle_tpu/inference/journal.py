"""Write-ahead request journal for the serving control plane (ISSUE 11;
reference analogs: the etcd/RocksDB WAL framing discipline — length +
CRC per record, torn tail tolerated, mid-file corruption fatal — and
vLLM-lineage serving systems' request-journal + snapshot recovery, where
the frontend's request lifecycle is the durable state and the tokens are
not: greedy determinism plus seeded, replayable sample streams make a
recovered request's output provably identical to a crash-free run).

Format: an append-only file of CRC-framed records,

    [u32 payload_len][u32 crc32(payload)][payload = compact JSON]

Three lifecycle record kinds (written by ``ServingFrontend``), plus one
compaction kind:

* ``admit``    — rid, prompt ids, ``SamplingParams`` wire dict, priority,
  remaining deadline seconds, token budget fields, idempotency key.
  Journaled at admission, BEFORE the request can reach a replica.
* ``progress`` — rid + tokens-generated count, appended at megastep
  boundaries.  Observability only: recovery re-prefills from the prompt
  and the tokens replay (they are deliberately NOT journaled).
* ``terminal`` — rid, typed ``RequestStatus`` value, token count,
  attempts, idempotency key.  Exactly one per admitted rid.
* ``epoch`` — the writer's fencing epoch (ISSUE 12), appended when an
  epoch-armed frontend arms a fresh journal; compaction snapshots carry
  the same field.  ``ServingFrontend.recover`` REFUSES a journal whose
  recorded epoch exceeds the recovering frontend's (the caller is the
  stale incarnation) and, absent an explicit epoch, arms at the
  journal's epoch + 1 — the journal-side half of the zombie fence.
* ``snapshot`` — whole-state record written by compaction
  (``rewrite``): open admits + the bounded keyed-terminal cache +
  ``next_rid`` + the writer epoch.  Replay = snapshot state, then the
  suffix records.

Failure semantics on replay (``replay``):

* an EMPTY file is a valid empty journal;
* a TORN TAIL — the file ends mid-header or mid-payload, the shape a
  crash mid-``append`` leaves — is tolerated: replay stops at the last
  complete record, and opening for append truncates the tear so new
  records never land after garbage;
* a complete frame whose CRC does not match (bit rot, concurrent
  writers, a wrong file) raises :class:`JournalCorruption` — corruption
  mid-file must fail LOUD, never be skipped, because every record after
  it is untrustworthy and "recovered" state built over it would silently
  drop or duplicate requests.

Durability knob: ``fsync=True`` (default) fsyncs every append — survives
machine crash; ``fsync=False`` leaves records in the OS page cache —
survives process SIGKILL (the kill-frontend chaos soak's failure model)
but not power loss.  Both I/O paths carry failpoints
(``journal.append``, ``journal.fsync`` — ``inference/faults.py``) so
chaos runs can fail the journal deterministically; the frontend reacts
by degrading to non-durable serving with a loud ``journal_degraded``
gauge, never by killing the data plane.
"""
from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["RequestJournal", "JournalCorruption", "JournalSuperseded",
           "recorded_epoch",
           "ADMIT", "PROGRESS", "TERMINAL", "SNAPSHOT", "EPOCH"]

_HDR = struct.Struct("<II")          # payload length, crc32(payload)
# a complete frame claiming a payload larger than this is corruption,
# not a big record (admit records are ~prompt-sized; snapshots are
# bounded by open requests + the keyed-terminal cache)
_MAX_RECORD = 64 * 1024 * 1024

ADMIT = "admit"
PROGRESS = "progress"
TERMINAL = "terminal"
SNAPSHOT = "snapshot"
EPOCH = "epoch"


class JournalCorruption(RuntimeError):
    """A complete mid-file record failed its CRC (or decode): everything
    after it is untrustworthy, so replay refuses to continue.  Carries
    the byte offset of the bad frame."""

    def __init__(self, path: str, offset: int, why: str):
        super().__init__(
            f"journal {path!r} corrupt at byte {offset}: {why} — refusing "
            "to skip-and-continue (records after a corrupt frame cannot be "
            "trusted); restore the file or start a fresh journal")
        self.path = path
        self.offset = offset


class JournalSuperseded(RuntimeError):
    """The file at ``path`` is no longer the one this journal instance
    owns: a successor incarnation recovered and compacted it (recovery
    always compacts, which ``os.replace``s the path with a NEW inode).
    Raised instead of writing — RPC-level epoch fencing cannot protect
    the journal FILE, so a resumed zombie's compaction would otherwise
    ``os.replace`` its stale snapshot over the successor's live WAL.
    Terminal for the writer: the frontend treats it like a worker fence
    (depose, stop journaling), not like a degradable I/O fault."""


class RequestJournal:
    """Append-only CRC-framed journal of the request lifecycle.

    >>> j = RequestJournal("/var/lib/paddle_tpu/requests.wal")
    >>> j.append({"t": "admit", "rid": 0, "prompt": [1, 5, 7], ...})
    >>> snapshot, records = RequestJournal(path).replay()

    The file handle opens lazily on first ``append`` (scanning the
    existing file and truncating any torn tail first, so appends never
    land after garbage).  ``rewrite`` is snapshot-based compaction:
    the new content is written to a sibling file and atomically
    ``os.replace``d over the journal.
    """

    def __init__(self, path, *, fsync: bool = True, fault_injector=None):
        from .faults import FaultInjector

        self.path = os.fspath(path)
        self.fsync_enabled = bool(fsync)
        self._faults = (fault_injector if fault_injector is not None
                        else FaultInjector.from_env())
        self._fh = None
        # (st_dev, st_ino) of the file this instance owns, recorded at
        # first open / after each compaction.  A mismatch with the path
        # later means a successor os.replace'd the journal — see
        # JournalSuperseded.  None until the first write.
        self._owned_id: Optional[Tuple[int, int]] = None
        # local instrumentation for tools/tests; the frontend keeps its
        # own registry counters (journal_records/bytes_total) from
        # append() return values rather than reading these
        self.records_appended = 0
        self.bytes_appended = 0
        self.compactions = 0

    # ------------------------------------------------------------- framing
    @staticmethod
    def _frame(rec: Dict) -> bytes:
        payload = json.dumps(rec, separators=(",", ":")).encode()
        if len(payload) > _MAX_RECORD:
            # enforce the cap at WRITE time too: a correctly-CRC'd frame
            # past the cap would be rejected by _scan as corruption, so
            # writing one would poison the whole journal (the frontend
            # turns this raise into degraded non-durable serving)
            raise ValueError(
                f"journal record of {len(payload)} bytes exceeds the "
                f"{_MAX_RECORD}-byte frame cap (snapshot of an unbounded "
                "open-request set? cap admission queues)")
        return _HDR.pack(len(payload), zlib.crc32(payload)) + payload

    def _scan(self) -> Tuple[List[Dict], int]:
        """Parse every complete record; returns (records, clean_end) where
        ``clean_end`` is the byte offset after the last complete record
        (< file size exactly when the tail is torn).  Raises
        :class:`JournalCorruption` on a complete frame with a bad CRC or
        undecodable payload."""
        records: List[Dict] = []
        if not os.path.exists(self.path):
            return records, 0
        with open(self.path, "rb") as f:
            data = f.read()
        off, size = 0, len(data)
        while off < size:
            if size - off < _HDR.size:
                break                                    # torn header
            length, crc = _HDR.unpack_from(data, off)
            if length > _MAX_RECORD:
                raise JournalCorruption(
                    self.path, off, f"frame claims {length} payload bytes "
                    f"(cap {_MAX_RECORD}) — length field is garbage")
            if size - off - _HDR.size < length:
                break                                    # torn payload
            payload = data[off + _HDR.size:off + _HDR.size + length]
            if zlib.crc32(payload) != crc:
                raise JournalCorruption(
                    self.path, off, "CRC mismatch on a complete frame")
            try:
                records.append(json.loads(payload))
            except ValueError as e:
                raise JournalCorruption(
                    self.path, off, f"payload is not valid JSON ({e})") \
                    from e
            off += _HDR.size + length
        return records, off

    # -------------------------------------------------------------- append
    def _check_owner(self):
        """Refuse to touch the path once it stopped being OUR file.
        Best-effort (a replace can still land between this check and the
        write), but the deterministic zombie case — the successor already
        recovered, which always compacts to a new inode — is caught."""
        if self._owned_id is None:
            return
        try:
            st = os.stat(self.path)
        except OSError as e:
            raise JournalSuperseded(
                f"journal {self.path!r} vanished from under its writer "
                "(moved or deleted) — a successor owns the path now; "
                "stop journaling") from e
        if (st.st_dev, st.st_ino) != self._owned_id:
            raise JournalSuperseded(
                f"journal {self.path!r} was replaced by another "
                "incarnation (recovery compaction installs a new inode) "
                "— this writer is the stale one; stop journaling")

    def _open_for_append(self):
        if self._fh is not None:
            return
        _, clean_end = self._scan()            # raises on real corruption
        fh = open(self.path, "ab")
        if fh.tell() != clean_end:
            # torn tail from a crash mid-append: truncate it so new
            # records are readable (appending after garbage would make
            # every later record unreachable to replay)
            fh.truncate(clean_end)
            fh.seek(clean_end)
        self._fh = fh
        if self._owned_id is None:
            st = os.fstat(fh.fileno())
            self._owned_id = (st.st_dev, st.st_ino)

    def _fsync(self):
        if self._faults is not None:
            self._faults.fire("journal.fsync", detail=self.path)
        if self.fsync_enabled:
            os.fsync(self._fh.fileno())

    def append(self, rec: Dict) -> int:
        """Frame + write (+ fsync per policy) one record; returns the
        bytes written.  Raises on any I/O fault — the caller (the
        frontend) owns the degrade-to-non-durable reaction."""
        return self.append_batch([rec])

    def append_batch(self, recs) -> int:
        """Group commit: frame + write every record, then ONE flush +
        fsync for the whole batch.  The frontend batches the per-request
        PROGRESS records of one control step through here — per-record
        fsync on the decode hot path would cost one synchronous disk
        barrier per active request per megastep, handing back the host-
        sync win megastep decode exists for.  (Batch durability is
        all-or-torn-tail: a crash mid-batch loses a suffix of it, which
        replay already tolerates.)  The ``journal.append`` failpoint
        still fires per record so chaos schedules see stable traversal
        counts."""
        frames = []
        for rec in recs:
            if self._faults is not None:
                self._faults.fire("journal.append",
                                  detail=str(rec.get("t", "")))
            frames.append(self._frame(rec))
        if not frames:
            return 0
        # one stat per group commit: a resumed zombie with its handle
        # still OPEN would otherwise keep "successfully" appending into
        # the orphaned inode after a successor os.replace'd the path —
        # the write cannot corrupt the successor, but the caller must
        # learn it is deposed, not get a silent no-op ack.  Also covers
        # the closed-then-reopened writer before _open_for_append would
        # land its records in the SUCCESSOR's live file.
        self._check_owner()
        self._open_for_append()
        for frame in frames:
            self._fh.write(frame)
        self._fh.flush()
        self._fsync()
        self.records_appended += len(frames)
        n = sum(len(f) for f in frames)
        self.bytes_appended += n
        return n

    # -------------------------------------------------------------- replay
    def replay(self) -> Tuple[Optional[Dict], List[Dict]]:
        """(snapshot record or None, lifecycle records after it).

        Tolerates an empty file and a torn tail; raises
        :class:`JournalCorruption` on a complete-but-bad mid-file frame.
        A snapshot anywhere but record 0 supersedes everything before it
        (compaction replaces the file atomically, so mid-file snapshots
        only appear if an operator concatenated journals — honoring the
        LAST one keeps that well-defined)."""
        records, _ = self._scan()
        snapshot = None
        suffix: List[Dict] = []
        for rec in records:
            if rec.get("t") == SNAPSHOT:
                snapshot, suffix = rec, []
            else:
                suffix.append(rec)
        return snapshot, suffix

    # ---------------------------------------------------------- compaction
    def rewrite(self, snapshot: Dict, suffix: Iterable[Dict] = ()):
        """Snapshot-based compaction: atomically replace the journal with
        ``snapshot`` (+ optional ``suffix`` records).  The write goes to
        a sibling temp file first, so a crash mid-compaction leaves the
        old journal intact.  Raises :class:`JournalSuperseded` instead of
        replacing a file another incarnation already installed over the
        path — the one journal write RPC epoch fencing cannot stop (a
        resumed zombie compacting would clobber the successor's WAL)."""
        self._check_owner()
        if self._faults is not None:
            self._faults.fire("journal.append", detail=SNAPSHOT)
        if snapshot.get("t") != SNAPSHOT:
            snapshot = dict(snapshot, t=SNAPSHOT)
        tmp = self.path + ".compact"
        frames = [self._frame(snapshot)] + [self._frame(r) for r in suffix]
        self.close()
        with open(tmp, "wb") as f:
            for fr in frames:
                f.write(fr)
            f.flush()
            # compaction's durability barrier traverses the same
            # failpoint as append-path fsyncs, so chaos schedules can
            # fail it (the frontend degrades, old journal stays intact)
            if self._faults is not None:
                self._faults.fire("journal.fsync", detail=tmp)
            if self.fsync_enabled:
                os.fsync(f.fileno())
        os.replace(tmp, self.path)
        if self.fsync_enabled:
            # the rename itself must be durable, or a machine crash could
            # resurrect the pre-compaction file
            try:
                dfd = os.open(os.path.dirname(os.path.abspath(self.path)),
                              os.O_RDONLY)
            except OSError:
                dfd = None
            if dfd is not None:
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
        self.compactions += 1
        self.records_appended += len(frames)
        self.bytes_appended += sum(len(fr) for fr in frames)
        # reopen for append directly: the file is exactly the frames just
        # written, so the lazy-open full-file rescan (a read+JSON-parse of
        # the snapshot on the serving control path right after every
        # compaction) is provably unnecessary here
        self._fh = open(self.path, "ab")
        st = os.fstat(self._fh.fileno())
        self._owned_id = (st.st_dev, st.st_ino)

    # ------------------------------------------------------------ lifecycle
    def close(self):
        if self._fh is not None:
            try:
                self._fh.flush()
            finally:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def recorded_epoch(journal) -> Optional[int]:
    """Highest writer epoch a journal records (the snapshot ``epoch``
    field or ``EPOCH`` records), or None for a pre-HA journal / missing
    file.  Standbys pass this as the acquisition FLOOR
    (``FrontendLease.acquire(min_epoch=...)``): if the lease record is
    lost while the fleet is at epoch N (KV master restart, an operator
    deleting the key), acquiring at epoch 1 would depose the healthy
    active AND be refused by the journal — a full outage that only
    heals one TTL per epoch increment.  The journal remembers N.

    This is a second full replay on the takeover path (``recover``
    replays again right after) — accepted: compaction every
    ``journal_compact_every`` records bounds the file to one snapshot
    plus a short suffix, and the floor is needed BEFORE ``acquire``,
    which is needed before ``recover`` may touch anything."""
    if not isinstance(journal, RequestJournal):
        journal = RequestJournal(journal)
    snapshot, records = journal.replay()
    epoch = None
    if snapshot is not None and snapshot.get("epoch") is not None:
        epoch = int(snapshot["epoch"])
    for rec in records:
        if rec.get("t") == EPOCH:
            epoch = max(epoch or 0, int(rec["epoch"]))
    return epoch
