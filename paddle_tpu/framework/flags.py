"""Runtime flag registry.

Capability parity with the reference's gflags-workalike
(/root/reference/paddle/common/flags.h:83 ``PD_DEFINE_*`` +
``paddle.set_flags/get_flags``): a process-wide registry of typed flags, each
overridable through a ``FLAGS_<name>`` environment variable at first read.
TPU-native difference: flags that matter to XLA (e.g. memory fraction) are
translated to XLA/JAX env settings rather than a custom allocator stack.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict

__all__ = ["define_flag", "set_flags", "get_flags"]

_lock = threading.Lock()


class _Flag:
    __slots__ = ("name", "value", "typ", "help", "env_read")

    def __init__(self, name: str, default: Any, typ: Callable, help: str):
        self.name = name
        self.value = default
        self.typ = typ
        self.help = help
        self.env_read = False


_registry: Dict[str, _Flag] = {}


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    raise ValueError(f"cannot parse bool flag value {v!r}")


def define_flag(name: str, default: Any, help: str = ""):
    typ: Callable
    if isinstance(default, bool):
        typ = _parse_bool
    elif isinstance(default, int):
        typ = int
    elif isinstance(default, float):
        typ = float
    else:
        typ = str
    with _lock:
        if name in _registry:
            raise ValueError(f"flag {name!r} already defined")
        _registry[name] = _Flag(name, default, typ, help)


def _flag(name: str) -> _Flag:
    key = name[6:] if name.startswith("FLAGS_") else name
    f = _registry.get(key)
    if f is None:
        raise KeyError(f"unknown flag: {name}")
    if not f.env_read:
        env = os.environ.get("FLAGS_" + f.name)
        if env is not None:
            f.value = f.typ(env)
        f.env_read = True
    return f


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags parity (python/paddle/base/framework.py)."""
    for k, v in flags.items():
        f = _flag(k)
        f.value = f.typ(v)
        f.env_read = True


def get_flags(flags) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        f = _flag(k)
        out["FLAGS_" + f.name] = f.value
    return out


def flag_value(name: str) -> Any:
    """Internal fast read used by framework code."""
    return _flag(name).value


# Core flags (subset of the reference's surface that is meaningful on TPU).
define_flag("check_nan_inf", False, "scan op outputs for nan/inf in eager mode")
define_flag("eager_op_jit", False, "run each eager op through a cached jax.jit")
define_flag("benchmark", False, "block on every op for precise timing")
define_flag("use_bf16_default", False, "make bfloat16 the default float dtype")
define_flag("dump_hlo", "", "directory to dump StableHLO + XLA-optimized HLO "
            "of every program compiled by TrainStep/to_static")
define_flag("flash_autotune", False, "measure flash-attention block sizes on "
            "first encounter of a new (seq, head_dim) instead of using the "
            "built-in table")
