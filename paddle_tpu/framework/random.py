"""RNG state management.

Reference capability: seeded ``phi::Generator`` per device
(/root/reference/paddle/phi/core/generator.h) plus the TP-aware
``RNGStatesTracker`` (/root/reference/python/paddle/distributed/fleet/layers/mpu/random.py:34).

TPU-native design: a functional threefry key chain. A ``Generator`` owns a JAX
PRNG key; every draw splits the chain (key = fold_in(key, counter)) so eager
ops stay reproducible without mutation-order hazards, and named tracker states
(``global_seed`` / ``local_seed``) fold in mesh coordinates so dropout masks can
be kept identical inside a TP group but distinct across it.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax

__all__ = ["Generator", "seed", "default_generator", "get_rng_state", "set_rng_state", "RNGStatesTracker"]


class Generator:
    """A splittable PRNG stream."""

    def __init__(self, seed_: int = 0):
        self._seed = int(seed_)
        # key creation is LAZY: touching jax.random at import time would
        # initialize the XLA backend before a multi-host program can call
        # jax.distributed.initialize() (see distributed/env.py)
        self._key_cache = None
        self._counter = 0

    @property
    def _key(self):
        if self._key_cache is None:
            self._key_cache = jax.random.key(self._seed)
        return self._key_cache

    def manual_seed(self, seed_: int):
        self._seed = int(seed_)
        self._key_cache = None
        self._counter = 0
        return self

    def next_key(self):
        """Return a fresh key; advances the stream. Inside a to_static trace
        the key comes from the trace context (a traced input), so compiled
        functions re-randomize per call instead of baking one mask."""
        try:
            from ..jit import trace_state

            ctx = trace_state.current()
            if ctx is not None:
                return ctx.next_key()
        except ImportError:
            pass
        self._counter += 1
        return jax.random.fold_in(self._key, self._counter)

    def peek_key(self):
        return jax.random.fold_in(self._key, self._counter + 1)

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        self._seed, self._counter = state
        self._key_cache = None
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(s: int) -> Generator:
    """paddle.seed parity: seed the global generator (and tracker streams)."""
    _default_generator.manual_seed(s)
    _tracker.reset_base(s)
    return _default_generator


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


class RNGStatesTracker:
    """Named RNG streams for tensor-parallel dropout parity.

    Mirrors fleet's RNGStatesTracker contract: ``global_seed`` streams are
    identical across all model-parallel ranks (same dropout mask), while
    ``local_seed`` streams fold in the mp coordinate so each rank differs.
    """

    def __init__(self):
        self._gens: Dict[str, Generator] = {}
        self._base = 0

    def reset_base(self, base_seed: int):
        self._base = int(base_seed)
        self._gens.clear()

    def add(self, name: str, seed_: int):
        if name in self._gens:
            raise ValueError(f"rng state {name!r} already exists")
        self._gens[name] = Generator(seed_)

    def get_states_tracker(self):
        return {k: g.get_state() for k, g in self._gens.items()}

    def set_states_tracker(self, states):
        for k, st in states.items():
            self._gens.setdefault(k, Generator()).set_state(st)

    def generator(self, name: str) -> Generator:
        if name not in self._gens:
            # derive deterministically from the base seed and the name hash
            self._gens[name] = Generator(self._base + (hash(name) % (1 << 30)))
        return self._gens[name]

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        """Context manager: random ops inside draw from the named stream."""
        global _default_generator
        prev = _default_generator
        _default_generator = self.generator(name)
        try:
            yield
        finally:
            _default_generator = prev


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker
