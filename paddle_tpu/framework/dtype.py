"""Dtype system for paddle_tpu.

TPU-native rethink of the reference's ``phi::DataType`` enum
(/root/reference/paddle/phi/common/data_type.h): instead of a closed C++ enum we
keep a small registry of ``DType`` singletons that wrap numpy/jax dtypes, so the
whole stack (Tensor meta, AMP lists, checkpoint IO) speaks one vocabulary while
XLA sees plain ``jnp`` dtypes.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = [
    "DType",
    "dtype",
    "bool_",
    "uint8",
    "int8",
    "int16",
    "int32",
    "int64",
    "float16",
    "bfloat16",
    "float32",
    "float64",
    "complex64",
    "complex128",
    "convert_dtype",
    "to_jax_dtype",
    "get_default_dtype",
    "set_default_dtype",
]


class DType:
    """A framework dtype: name + numpy/jax dtype. Singleton per kind."""

    __slots__ = ("name", "np_dtype")
    _registry: dict = {}

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        DType._registry[name] = self

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            try:
                return self.name == convert_dtype(other).name
            except (TypeError, ValueError):
                return False
        try:
            return self.np_dtype == np.dtype(other)
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(self.name)

    @property
    def itemsize(self) -> int:
        return self.np_dtype.itemsize

    @property
    def is_floating_point(self) -> bool:
        return self.name in ("float16", "bfloat16", "float32", "float64")

    @property
    def is_integer(self) -> bool:
        return self.name in ("uint8", "int8", "int16", "int32", "int64")

    @property
    def is_complex(self) -> bool:
        return self.name in ("complex64", "complex128")


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", jnp.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)

try:  # fp8 tier (reference: phi float8_e4m3fn/e5m2 types)
    import ml_dtypes as _mld

    float8_e4m3fn = DType("float8_e4m3fn", _mld.float8_e4m3fn)
    float8_e5m2 = DType("float8_e5m2", _mld.float8_e5m2)
except ImportError:  # pragma: no cover
    float8_e4m3fn = float8_e5m2 = None

# canonical aliases accepted from user code
_ALIASES = {
    "bool": "bool",
    "bool_": "bool",
    "uint8": "uint8",
    "int8": "int8",
    "int16": "int16",
    "int32": "int32",
    "int64": "int64",
    "float16": "float16",
    "half": "float16",
    "bfloat16": "bfloat16",
    "bf16": "bfloat16",
    "float32": "float32",
    "float": "float32",
    "float64": "float64",
    "double": "float64",
    "complex64": "complex64",
    "complex128": "complex128",
}

if float8_e4m3fn is not None:
    _ALIASES["float8_e4m3fn"] = "float8_e4m3fn"
    _ALIASES["float8_e5m2"] = "float8_e5m2"


def convert_dtype(d) -> DType:
    """Convert any dtype-like (DType, str, numpy dtype, jnp dtype) to DType."""
    if isinstance(d, DType):
        return d
    if isinstance(d, str):
        key = _ALIASES.get(d)
        if key is None:
            raise ValueError(f"Unknown dtype string: {d!r}")
        return DType._registry[key]
    # numpy / jax dtypes
    npd = np.dtype(d)
    name = npd.name
    if name in DType._registry:
        return DType._registry[name]
    raise ValueError(f"Unsupported dtype: {d!r}")


def to_jax_dtype(d):
    if d is None:
        return None
    npd = convert_dtype(d).np_dtype
    # TPU-native default: without jax x64, int64/uint64 requests quietly become
    # 32-bit (indices are int32 on TPU; avoids per-op truncation warnings).
    import jax

    if not jax.config.jax_enable_x64 and npd in (np.dtype(np.int64), np.dtype(np.uint64)):
        return np.dtype(np.int32) if npd == np.dtype(np.int64) else np.dtype(np.uint32)
    return npd


_default_dtype = float32


def get_default_dtype() -> str:
    return _default_dtype.name


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if not d.is_floating_point:
        raise TypeError(f"default dtype must be floating point, got {d}")
    _default_dtype = d


def default_float_dtype() -> DType:
    return _default_dtype


# `paddle.dtype` style callable
def dtype(d) -> DType:  # noqa: A001 - mirrors reference API name
    return convert_dtype(d)
