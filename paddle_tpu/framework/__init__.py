"""Framework core: dtypes, flags, RNG (parity: python/paddle/framework + base)."""
from .dtype import (  # noqa: F401
    DType,
    bfloat16,
    bool_,
    float8_e4m3fn,
    float8_e5m2,
    complex128,
    complex64,
    convert_dtype,
    float16,
    float32,
    float64,
    get_default_dtype,
    int16,
    int32,
    int64,
    int8,
    set_default_dtype,
    to_jax_dtype,
    uint8,
)
from .flags import define_flag, get_flags, set_flags  # noqa: F401
from .random import (  # noqa: F401
    Generator,
    default_generator,
    get_rng_state,
    get_rng_state_tracker,
    seed,
    set_rng_state,
)

# keep the submodules reachable as attributes (the `random`/`dtype` names above
# must not shadow them for `from ..framework import dtype` module imports)
from . import dtype  # noqa: F401,E402
from . import flags  # noqa: F401,E402
from . import random  # noqa: F401,E402
