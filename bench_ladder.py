#!/usr/bin/env python
"""BASELINE ladder rungs beyond the flagship (BASELINE.md configs):
ResNet-50 ImageNet-shape training imgs/sec/chip and BERT-base-class finetune
step time. Prints one JSON line per rung. The flagship Llama rung stays in
bench.py (the driver's single-line contract).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import numpy as np


def host_fingerprint():
    """Identity of the machine a wall-clock rung was measured on.  The
    perf gate treats 'host' as a measurement-config key: rungs recorded
    on different hosts re-baseline loudly instead of being compared —
    r7 measured the SAME seed code 1.6-2.2x apart across two 'cpu'
    dev containers, so cross-host CPU numbers are garbage to gate on."""
    import platform

    model = "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    slug = "".join(c if c.isalnum() else "-" for c in model)[:40].strip("-")
    return f"{platform.machine()}-{os.cpu_count()}c-{slug}"


def _timeit(step, args, steps):
    """Multi-step timing: the whole window runs as ONE compiled scan
    (TrainStep.run_steps), so per-dispatch host overhead — large for models
    with hundreds of small param tensors on a remote accelerator — is paid
    once, as a real serving/training loop would."""
    import numpy as np

    stacks = [a.__class__(jnp_broadcast(a, steps)) for a in args]
    losses = step.run_steps(*stacks)  # compile + run
    losses.numpy()
    t0 = time.perf_counter()
    losses = step.run_steps(*stacks)
    ls = losses.numpy()
    return (time.perf_counter() - t0) / steps, float(ls[-1])


def jnp_broadcast(t, k):
    import jax.numpy as jnp

    v = t._value
    return jnp.broadcast_to(v, (k, *v.shape))


def bench_resnet50():
    import paddle_tpu as P
    from paddle_tpu.vision.models import resnet50

    backend = jax.default_backend()
    on_accel = backend in ("tpu", "axon")
    P.seed(0)
    batch = 128 if on_accel else 4
    size = 224 if on_accel else 32
    steps = 10 if on_accel else 2
    model = resnet50(num_classes=1000)
    if on_accel:
        model.bfloat16()
    opt = P.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                               parameters=model.parameters(),
                               multi_precision=on_accel)
    step = P.jit.TrainStep(
        model, lambda m, x, y: P.nn.functional.cross_entropy(m(x), y), opt)
    x = P.to_tensor(np.random.RandomState(0).rand(batch, 3, size, size).astype(np.float32))
    if on_accel:
        x = x.astype("bfloat16")
    y = P.to_tensor(np.random.RandomState(1).randint(0, 1000, (batch,)).astype(np.int64))
    dt, loss = _timeit(step, (x, y), steps)
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(batch / dt, 1),
        "unit": "imgs/s",
        "extra": {"backend": backend, "host": host_fingerprint(),
                  "batch": batch, "img": size,
                  "step_ms": round(dt * 1e3, 2), "loss": loss},
    }))


def bench_bert_base():
    import paddle_tpu as P
    from paddle_tpu import nn

    backend = jax.default_backend()
    on_accel = backend in ("tpu", "axon")
    P.seed(0)
    if on_accel:
        h, layers, heads, seq, batch, vocab, steps = 768, 12, 12, 128, 32, 30522, 10
    else:
        h, layers, heads, seq, batch, vocab, steps = 64, 2, 4, 32, 4, 512, 2

    class BertClassifier(nn.Layer):
        """BERT-base-shape encoder + pooler + 2-way head (finetune config)."""

        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(vocab, h)
            self.pos = nn.Embedding(seq, h)
            enc_layer = nn.TransformerEncoderLayer(h, heads, 4 * h, dropout=0.1,
                                                   activation="gelu")
            self.encoder = nn.TransformerEncoder(enc_layer, layers)
            self.cls = nn.Linear(h, 2)

        def forward(self, ids):
            import paddle_tpu as P

            x = self.embed(ids) + self.pos(P.arange(seq).astype("int32"))
            return self.cls(self.encoder(x)[:, 0])

    model = BertClassifier()
    if on_accel:
        model.bfloat16()
    opt = P.optimizer.AdamW(learning_rate=2e-5, parameters=model.parameters(),
                            multi_precision=on_accel)
    step = P.jit.TrainStep(
        model, lambda m, ids, y: P.nn.functional.cross_entropy(m(ids), y), opt)
    ids = P.to_tensor(np.random.RandomState(0).randint(0, vocab, (batch, seq)).astype(np.int32))
    y = P.to_tensor(np.random.RandomState(1).randint(0, 2, (batch,)).astype(np.int64))
    dt, loss = _timeit(step, (ids, y), steps)
    print(json.dumps({
        "metric": "bert_base_finetune_step_ms",
        "value": round(dt * 1e3, 2),
        "unit": "ms/step",
        "extra": {"backend": backend, "host": host_fingerprint(),
                  "batch": batch, "seq": seq,
                  "examples_per_sec": round(batch / dt, 1), "loss": loss},
    }))


def bench_llama_decode():
    """Serving decode rung: static-KV-cache autoregressive generation on the
    ~1B flagship (ideal is HBM-bound: all params stream per token)."""
    import paddle_tpu as P
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, greedy_decode

    backend = jax.default_backend()
    on_accel = backend in ("tpu", "axon")
    P.seed(0)
    if on_accel:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2560, intermediate_size=8192,
                          num_hidden_layers=9, num_attention_heads=10,
                          max_position_embeddings=2048, dtype="bfloat16")
        batch, prompt, new = 8, 128, 64
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128, intermediate_size=352,
                          num_hidden_layers=2, num_attention_heads=4,
                          max_position_embeddings=256)
        batch, prompt, new = 2, 8, 8
    model = LlamaForCausalLM(cfg)
    if on_accel:
        model.bfloat16()
    model.eval()
    ids = P.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, prompt)).astype(np.int32))

    # whole decode loop compiled into ONE program. Per-step time comes from
    # the SLOPE between two decode lengths: through a remote/tunneled chip a
    # single call carries a large fixed dispatch+sync overhead (measured
    # ~130 ms here) that is an artifact of the dev link, not the serving
    # step — the slope isolates the real per-token cost.
    ring = prompt + (3 * new if on_accel else new)

    def run(n):
        out = greedy_decode(model, ids, max_new_tokens=n, max_length=ring)
        out.numpy()  # compile + warm
        best = 1e9
        # CPU hosts: the whole call is ~4 ms, so a single timed repeat is
        # one scheduler preemption away from a 2x misread (r10 measured
        # 2.4k-4.1k tok/s across identical runs) — best-of-5 picks the
        # un-preempted call, same hardening the serving rung got in r8
        for _ in range(2 if on_accel else 5):
            t0 = time.perf_counter()
            out = greedy_decode(model, ids, max_new_tokens=n, max_length=ring)
            out.numpy()
            best = min(best, time.perf_counter() - t0)
        return best

    t_lo = run(new)
    if on_accel:
        t_hi = run(3 * new)
        per_step = (t_hi - t_lo) / (2 * new)
    else:
        per_step = t_lo / new
    tps = batch / per_step
    print(json.dumps({
        "metric": "llama_1b_decode_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "extra": {"backend": backend, "host": host_fingerprint(),
                  "batch": batch, "prompt": prompt,
                  "new_tokens": new, "ring": ring,
                  "ms_per_token_per_seq": round(per_step * 1e3, 2),
                  "method": "slope over decode lengths (removes fixed "
                            "dispatch overhead of the tunneled dev chip); "
                            "best-of-5 timed calls per point on CPU hosts",
                  "single_call_s": round(t_lo, 3)},
    }))


def bench_serving_mixed():
    """Continuous-batching serving rung (VERDICT r4 item 1): steady-state
    full-batch decode over the paged-KV cache with MIXED per-sequence
    context lengths. Device cost comes from an in-graph lax.scan of the
    engine's pure-decode step (one program, n steps) timed by the SLOPE
    between two scan lengths — the only valid method through the tunneled
    dev chip (PROFILE_r04.md). A short engine.run() with staggered
    admissions cross-checks end-to-end behavior."""
    import jax.numpy as jnp
    from jax import lax

    import paddle_tpu as P
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    backend = jax.default_backend()
    on_accel = backend in ("tpu", "axon")
    P.seed(0)
    if on_accel:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2560,
                          intermediate_size=8192, num_hidden_layers=9,
                          num_attention_heads=10,
                          max_position_embeddings=2048, dtype="bfloat16")
        B, block, budget, max_seq = 8, 64, 64, 448
        ctx0 = [128, 192, 256, 320, 128, 192, 256, 320]  # mixed lengths
        # scan lengths kept small: the tunneled remote-compile service
        # breaks (broken pipe) on the larger 32/96-iteration scan programs
        n_lo, n_hi = 8, 24
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=352, num_hidden_layers=2,
                          num_attention_heads=4, max_position_embeddings=256)
        B, block, budget, max_seq = 4, 8, 16, 64
        ctx0 = [8, 12, 16, 20]
        n_lo, n_hi = 4, 12
    model = LlamaForCausalLM(cfg)
    if on_accel:
        model.bfloat16()
    model.eval()
    eng = ServingEngine(model, max_batch_size=B, max_seq_len=max_seq,
                        block_size=block, token_budget=budget)

    # fill the paged caches to the mixed context lengths via real prefills
    rng = np.random.RandomState(0)
    for c in ctx0:
        eng.add_request(rng.randint(0, cfg.vocab_size, (c,)).tolist(),
                        max_new_tokens=max_seq - c - 1)
    eng.step()  # admission happens inside step()
    while eng._queue or any(r.in_prefill for r in eng._active.values()):
        eng.step()

    # steady-state decode: scan the raw step body n times in ONE program.
    # Engine decode convention: the freshly sampled token is fed (and its
    # KV cached) at position context_len - 1.
    enc = jnp.zeros((B,), jnp.int32)
    now = jnp.ones((B,), jnp.int32)
    cu = jnp.arange(B + 1, dtype=jnp.int32)
    bt = jnp.asarray(eng.block_tables)
    by_slot = sorted(eng._active.values(), key=lambda r: r.slot)
    dec0 = jnp.asarray([r.context_len - 1 for r in by_slot], jnp.int32)
    toks0 = jnp.asarray([r.generated[-1] for r in by_slot], jnp.int32)

    def body(weights, carry, _):
        toks, kcs, vcs, dec = carry
        nxt, kcs, vcs, _ = eng._step_raw(
            weights, kcs, vcs, eng._rope, toks, enc, dec, now, cu,
            bt, 1)
        return (nxt, kcs, vcs, dec + 1), nxt[0]

    progs = {}  # one compile per scan length, shared across slope repeats

    def run_n(n):
        prog = progs.get(n)
        if prog is None:
            @jax.jit
            def prog(weights, kcs, vcs):
                # weights MUST be arguments: closing over the ~2 GB pytree
                # embeds it as program constants, which the tunneled remote
                # compile service cannot swallow (broken pipe)
                (_, kcs, vcs, _), out = lax.scan(
                    lambda c, x: body(weights, c, x),
                    (toks0, list(kcs), list(vcs), dec0), None, length=n)
                return out[-1]
            progs[n] = prog
        o = prog(eng._weights, eng.key_caches, eng.value_caches)  # compile/warm
        float(o)
        best = 1e9
        for _ in range(4):
            t0 = time.perf_counter()
            float(prog(eng._weights, eng.key_caches, eng.value_caches))
            best = min(best, time.perf_counter() - t0)
        return best

    # the slope SUBTRACTS two noisy minima, so scheduler jitter on a
    # shared-vCPU host amplifies: r8 measured 13.5k vs 24.2k tok/s on
    # identical code back-to-back with the old best-of-2 single slope.
    # Harden: best-of-4 per point, 3 full slope repeats, keep the min
    # POSITIVE per-step (the least-interference estimate) — a repeat whose
    # subtraction goes non-positive is pure interference and is discarded,
    # not clamped (a clamped 1e-9 inside the min would win and record an
    # absurd ~1e10 tok/s baseline)
    pairs = [(run_n(n_lo), run_n(n_hi)) for _ in range(3)]
    positive = [(hi - lo) / (n_hi - n_lo) for lo, hi in pairs if hi > lo]
    if positive:
        per_step, slope_fallback = min(positive), False
    else:
        # every repeat's subtraction went non-positive (pathological host
        # interference): fall back to whole-scan time over steps — it
        # folds the fixed dispatch overhead in (underestimates tok/s,
        # never records an absurd 1e10 baseline the gate would then hold
        # every honest round against)
        per_step, slope_fallback = min(hi for _, hi in pairs) / n_hi, True
    tps = B / per_step

    # end-to-end cross-check: staggered mixed-length service completes
    eng2 = ServingEngine(model, max_batch_size=B, max_seq_len=max_seq,
                         block_size=block, token_budget=budget)
    pr = [rng.randint(0, cfg.vocab_size, (c,)).tolist()
          for c in ([5, 17, 9, 13] if not on_accel else [64, 200, 96, 150])]
    t0 = time.perf_counter()
    outs = {}
    r0 = eng2.add_request(pr[0], max_new_tokens=8)
    r1 = eng2.add_request(pr[1], max_new_tokens=8)
    eng2.step()
    r2 = eng2.add_request(pr[2], max_new_tokens=8)
    r3 = eng2.add_request(pr[3], max_new_tokens=8)
    outs = eng2.run()
    e2e_s = time.perf_counter() - t0
    ok = all(len(outs[r]) == 8 for r in (r0, r1, r2, r3))

    print(json.dumps({
        "metric": "serving_mixed_decode_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "extra": {"backend": backend, "host": host_fingerprint(),
                  "batch": B, "ctx_lengths": ctx0,
                  "block_size": block, "paged_cache": True,
                  "ms_per_step": round(per_step * 1e3, 3),
                  "slope_fallback": slope_fallback,
                  "method": "min over 3 slope repeats, in-graph scan "
                            f"lengths {n_lo} vs {n_hi} steps, best-of-4 "
                            "per point",
                  "e2e_staggered_admission_ok": ok,
                  "e2e_wallclock_s_incl_tunnel_dispatch": round(e2e_s, 2)},
    }))


def _load_bench_serving():
    """tools/bench_serving.py by path (it is a script dir, not a package)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_serving",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tools", "bench_serving.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def bench_serving_frontend():
    """Serving control-plane rung (ISSUE 2): open-loop Poisson arrivals
    through ServingFrontend (admission, priority routing, preemption under
    a deliberately tight block pool) — steady-state tokens/s plus p50/p95
    TTFT. The heavy lifting lives in tools/bench_serving.py; this rung
    re-emits its JSON line so the perf gate sees it in the ladder."""
    print(json.dumps(_load_bench_serving().run_bench()))


def bench_serving_fleet():
    """Cross-host fleet rung (ISSUE 3): the frontend rung's open-loop
    Poisson workload, but served by 2 remote serving_worker.py processes
    over the RPC stack instead of in-process replicas — measures what the
    per-step HTTP round trips and state-mirror sync cost against the
    in-process number directly above it in the ladder."""
    print(json.dumps(_load_bench_serving().run_bench_fleet(workers=2)))


def bench_serving_prefix():
    """Prefix-cache rung (ISSUE 5): a shared-system-prompt request stream
    served cache-off then cache-on; value = the ratio of prefill tokens
    actually computed (deterministic engine counters, lower is better).
    Greedy parity across modes is asserted inside the bench — a rung that
    'wins' by emitting different tokens fails instead of recording."""
    print(json.dumps(_load_bench_serving().run_bench_prefix()))


def bench_serving_disagg():
    """Disaggregation rung (ISSUE 17): concurrent identical prompts
    served colocated (2 decode replicas) vs split (prefill replica + the
    same decode replicas over the KV fabric); value = the ratio of
    fleet-wide prefill tokens actually computed (deterministic engine
    counters, lower is better — transferred blocks are written, not
    computed).  Greedy parity across modes is asserted inside the
    bench."""
    print(json.dumps(_load_bench_serving().run_bench_disagg()))


def bench_serving_megastep():
    """Megastep rung (ISSUE 9): a closed request batch served with K-step
    in-graph decode vs per-token stepping; value = host round trips per
    generated token with the megastep on (deterministic scheduling
    counters, lower is better, bound = prefill steps + 1/K).  Token
    parity megastep-on vs -off is asserted inside the bench."""
    print(json.dumps(_load_bench_serving().run_bench_megastep()))


def bench_serving_megastep_saturated():
    """Saturated megastep rung (ISSUE 16): open-loop Poisson STAGGERED
    admission in virtual engine-step time — the traffic shape where the
    r11 megastep disarmed (some row always prefilling) and the engine
    degraded toward per-token stepping.  With the mixed-phase scan the
    megastep stays armed; value = host round trips per emitted token
    with megastep on (deterministic counters).  Greedy AND seeded parity
    megastep-on vs -off are asserted inside the bench, and the run fails
    unless at least one mixed launch actually armed."""
    print(json.dumps(_load_bench_serving().run_bench_staggered()))


def bench_pipeline_compiled_vs_eager():
    """Compiled-vs-eager pipeline rung: the same dp2×mp2×pp2 llama microbatch
    schedule through the eager per-op 1F1B engine vs CompiledPipelineTrainStep
    (one XLA program). Runs on a virtual 8-device CPU mesh in a subprocess —
    pipeline parallelism needs >1 device, and the comparison (host-dispatch
    overhead vs one fused program) is the quantity of interest."""
    import subprocess

    child = os.environ.get("_PADDLE_TPU_PP_BENCH_CHILD") == "1"
    if not child:
        env = dict(os.environ)
        env["_PADDLE_TPU_PP_BENCH_CHILD"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=8")
        env["XLA_FLAGS"] = " ".join(flags)
        for k in list(env):
            if k.startswith(("TPU_", "LIBTPU", "AXON")):
                env.pop(k)
        subprocess.run([sys.executable, os.path.abspath(__file__), "pipeline"],
                       env=env, check=True)
        return

    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as P
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.meta_parallel import (
        CompiledPipelineTrainStep,
        PipelineLayer,
    )
    from paddle_tpu.models import (
        LlamaPretrainingCriterion,
        llama_pipeline_descs,
        llama_tiny,
    )

    P.seed(0)
    # old jax cannot mix the compiled pipeline's manual 'pp' axis with
    # size>1 auto axes (see compiled_pipeline._pp_collectives_native) —
    # degrade to a pp-only mesh there so the rung stays measurable; the
    # mesh used is recorded in extra.mesh
    dmp = 2 if hasattr(_jax, "shard_map") else 1
    s = dist.fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": dmp, "mp_degree": dmp, "pp_degree": 2,
                        "sharding_degree": 1, "sep_degree": 1}
    s.pipeline_configs = {"accumulate_steps": 4, "schedule_mode": "1F1B"}
    dist.fleet.init(is_collective=True, strategy=s)
    cfg = llama_tiny()
    crit = LlamaPretrainingCriterion()
    pipe = PipelineLayer(layers=llama_pipeline_descs(cfg), num_stages=2,
                         loss_fn=lambda lo, la: crit(lo, la))
    model = dist.fleet.distributed_model(pipe)
    opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    ids = P.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (8, 32)).astype(np.int32))
    reps = 5
    model.train_batch([ids, ids], opt)  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        loss_e = model.train_batch([ids, ids], opt)
    float(loss_e.numpy())
    eager_ms = (time.perf_counter() - t0) / reps * 1e3

    cstep = CompiledPipelineTrainStep(pipe, getattr(opt, "_inner", opt),
                                      num_micro=4)
    float(cstep(ids, ids).numpy())  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        loss_c = cstep(ids, ids)
    float(loss_c.numpy())
    comp_ms = (time.perf_counter() - t0) / reps * 1e3
    print(json.dumps({
        "metric": "pp_llama_step_ms_compiled_vs_eager",
        "value": round(comp_ms, 2),
        "unit": "ms/step",
        "extra": {"backend": "cpu-mesh-8dev", "host": host_fingerprint(),
                  "mesh": f"dp{dmp}.mp{dmp}.pp2",
                  "eager_step_ms": round(eager_ms, 2),
                  "speedup_vs_eager": round(eager_ms / comp_ms, 2),
                  "num_micro": 4},
    }))


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "resnet"):
        bench_resnet50()
    if which in ("all", "bert"):
        bench_bert_base()
    if which in ("all", "decode"):
        bench_llama_decode()
    if which in ("all", "serving"):
        bench_serving_mixed()
    if which in ("all", "frontend"):
        bench_serving_frontend()
    if which in ("all", "fleet"):
        bench_serving_fleet()
    if which in ("all", "prefix"):
        bench_serving_prefix()
    if which in ("all", "disagg"):
        bench_serving_disagg()
    if which in ("all", "megastep"):
        bench_serving_megastep()
    if which in ("all", "megastep_saturated"):
        bench_serving_megastep_saturated()
    if which in ("all", "pipeline"):
        bench_pipeline_compiled_vs_eager()
